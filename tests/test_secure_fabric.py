"""The secure transport AS the node fabric (driver tier).

Round-2 built the authenticated channel (test_secure_transport.py proves
the handshake/AEAD properties in isolation); these tests prove the
capability the reference actually has — *every* wire of a running node
ensemble is the authenticated transport (ArtemisTcpTransport.kt:1-60,
ArtemisMessagingServer.kt:132-376): P2P flows, notarisation, RPC and the
out-of-process verifier all ride it, and an uncertified peer is refused
at handshake before touching any queue.
"""

import time

import pytest

from corda_tpu.crypto import generate_keypair
from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
from corda_tpu.flows.api import class_path
from corda_tpu.ledger import CordaX500Name
from corda_tpu.messaging import (
    BrokerMessagingClient,
    DurableQueueBroker,
    HandshakeError,
    SecureBrokerServer,
    SecureFabricClient,
)
from corda_tpu.node.certificates import (
    dev_trust_root,
    issue_identity,
    load_identity,
    node_certificates,
    save_identity,
)
from corda_tpu.testing import driver

from corda_tpu.messaging import SECURE_TRANSPORT_AVAILABLE

# gate on the ACTUAL capability, both halves: the secure transport must
# be functional (importable cryptography + a working issue/verify probe —
# a broken OpenSSL binding imports fine and fails every operation), and
# the environment must be able to bind sockets / spawn processes for the
# fabric broker tiers. Either gap skips with its reason instead of failing.
from conftest import node_process_capability, secure_transport_capability

pytestmark = [
    pytest.mark.skipif(
        not SECURE_TRANSPORT_AVAILABLE,
        reason="secure transport needs the 'cryptography' package",
    ),
    pytest.mark.skipif(
        bool(secure_transport_capability()),
        reason=secure_transport_capability() or "",
    ),
    pytest.mark.skipif(
        bool(node_process_capability()),
        reason=node_process_capability() or "",
    ),
]


class TestCertificates:
    def test_issue_save_load_round_trip(self, tmp_path):
        ident = issue_identity("O=Node,L=London,C=GB", generate_keypair())
        save_identity(tmp_path / "certificates", ident)
        loaded = load_identity(tmp_path / "certificates")
        assert loaded.certificate == ident.certificate
        assert loaded.keypair.private == ident.keypair.private
        assert loaded.certificate.verify(loaded.trust_root)

    def test_node_certificates_persist_identity(self, tmp_path):
        a = node_certificates(tmp_path, "O=Node,L=London,C=GB")
        b = node_certificates(tmp_path, "O=Node,L=London,C=GB")
        assert a.keypair.public == b.keypair.public  # restart keeps identity

    def test_node_certificates_wrong_name_rejected(self, tmp_path):
        node_certificates(tmp_path, "O=Node,L=London,C=GB")
        with pytest.raises(ValueError, match="are for"):
            node_certificates(tmp_path, "O=Other,L=London,C=GB")

    def test_production_mode_refuses_auto_provision(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="devMode"):
            node_certificates(
                tmp_path, "O=Node,L=London,C=GB", dev_mode=False
            )


def _fabric_server(broker):
    ident = issue_identity("O=BrokerHost,L=Zurich,C=CH", generate_keypair())
    return ident, SecureBrokerServer(
        broker, ident.certificate, ident.keypair.private, ident.trust_root
    )


def _fabric_client(address, org):
    ident = issue_identity(f"O={org},L=London,C=GB", generate_keypair())
    return ident, SecureFabricClient(
        address, ident.certificate, ident.keypair.private, ident.trust_root
    )


class TestSecureFabricClient:
    def test_publish_consume_ack_over_fabric(self):
        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        try:
            _, fab = _fabric_client(server.address, "Peer")
            fab.publish("q", b"payload-1")
            msg = fab.consume("q", timeout=1.0)
            assert msg.payload == b"payload-1"
            # sender is the CHANNEL identity, not caller-controlled
            assert "O=Peer" in msg.sender
            fab.ack(msg.msg_id)
            assert fab.depth("q") == 0
            fab.close()
        finally:
            server.close()
            broker.close()

    def test_uncertified_peer_refused_before_broker_access(self):
        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        try:
            rogue_ca = generate_keypair()  # NOT the network trust root
            kp = generate_keypair()
            ident = issue_identity("O=Rogue,L=Nowhere,C=GB", kp, ca=rogue_ca)
            # the server rejects the rogue's auth leg and tears the socket
            # down; depending on timing the client sees that at construction
            # or on its first operation — either way NOTHING reaches the
            # broker
            with pytest.raises((HandshakeError, ConnectionError)):
                fab = SecureFabricClient(
                    server.address, ident.certificate, ident.keypair.private,
                    dev_trust_root().public,
                )
                fab.publish("q", b"intrusion")
            assert broker.depth("q") == 0
        finally:
            server.close()
            broker.close()

    def test_spoofed_envelope_sender_dropped(self):
        """A certified-but-malicious peer cannot SPEAK AS someone else:
        the fabric stamps each message with the channel identity, and the
        receiving client drops any envelope claiming a different sender —
        mutual auth extends to per-message attribution, as in the
        reference where the broker enforces the sender's queue identity."""
        import json as _json

        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        try:
            mallory_ident, mallory = _fabric_client(server.address, "Mallory")
            victim_ident, victim_fab = _fabric_client(server.address, "Victim")
            victim_name = str(victim_ident.party.name)
            endpoint = BrokerMessagingClient(victim_fab, victim_name)
            got = []
            endpoint.add_handler("t", lambda m, ack: (got.append(m), ack()))

            def framed(sender, body):
                header = _json.dumps({"topic": "t", "sender": sender}).encode()
                return len(header).to_bytes(4, "big") + header + body

            # spoof: Mallory's channel, envelope claims the notary sent it
            mallory.publish(
                f"p2p.{victim_name}", framed("O=Notary, L=Zurich, C=CH", b"x")
            )
            # honest: envelope matches Mallory's channel identity
            mallory.publish(
                f"p2p.{victim_name}",
                framed(str(mallory_ident.party.name), b"y"),
            )
            deadline = time.monotonic() + 5
            while not got and time.monotonic() < deadline:
                time.sleep(0.02)
            time.sleep(0.3)  # give the spoof a chance to (wrongly) land
            assert [m.payload for m in got] == [b"y"]
            assert got[0].sender == str(mallory_ident.party.name)
            endpoint.stop()
            mallory.close()
            victim_fab.close()
        finally:
            server.close()
            broker.close()

    def test_certified_peer_cannot_drain_anothers_inbox(self):
        """Queue-level authorization: a certified-but-malicious peer may
        not consume (or even inspect) another party's addressed queues,
        and may not ack/nack messages it was never delivered — the broker
        side of the attribution boundary (reference: Artemis per-queue
        security roles, ArtemisMessagingServer.kt)."""
        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        try:
            vi, victim = _fabric_client(server.address, "Victim2")
            _, mallory = _fabric_client(server.address, "Mallory2")
            vq = f"p2p.{vi.party.name}"
            mallory.publish(vq, b"for-victim")  # sending TO someone is fine
            with pytest.raises(RuntimeError, match="NotAuthorized"):
                mallory.consume(vq, timeout=0.2)
            with pytest.raises(RuntimeError, match="NotAuthorized"):
                mallory.depth(vq)
            # victim consumes its own queue; mallory cannot settle it
            msg = victim.consume(vq, timeout=1.0)
            assert msg is not None and msg.payload == b"for-victim"
            with pytest.raises(RuntimeError, match="NotAuthorized"):
                mallory.ack(msg.msg_id)
            victim.ack(msg.msg_id)
            assert victim.depth(vq) == 0
            victim.close()
            mallory.close()
        finally:
            server.close()
            broker.close()

    def test_garbage_connections_never_wedge_the_server(self):
        """Hostile bytes at the listener — random frames, truncated
        handshakes, instant disconnects — must neither crash the accept
        loop nor block certified peers (the broker faces the network)."""
        import random
        import socket as _socket

        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        try:
            rng = random.Random(99)
            for i in range(12):
                s = _socket.create_connection(server.address, timeout=5)
                mode = i % 4
                try:
                    if mode == 0:
                        s.close()  # connect-and-drop
                        continue
                    if mode == 1:  # random frame of hostile length
                        s.sendall(
                            (2 ** 31 - 1).to_bytes(4, "big") + b"\xff" * 64
                        )
                    elif mode == 2:  # plausible length, garbage body
                        body = rng.randbytes(200)
                        s.sendall(len(body).to_bytes(4, "big") + body)
                    else:  # truncated: length promised, nothing sent
                        s.sendall((500).to_bytes(4, "big"))
                    s.close()
                except OSError:
                    pass
            # a certified peer still gets full service afterwards
            _, fab = _fabric_client(server.address, "PostFuzz")
            fab.publish("fz", b"still works")
            msg = fab.consume("fz", timeout=2.0)
            assert msg is not None and msg.payload == b"still works"
            fab.ack(msg.msg_id)
            fab.close()
        finally:
            server.close()
            broker.close()

    def test_client_reconnects_after_broker_restart(self):
        """The Artemis-bridge-retry role: the fabric server drops (restart
        on the same port), and the client's next operations re-handshake
        and continue — consumers see one empty poll, publishes retry
        through the reconnect."""
        host_ident = issue_identity("O=RHost,L=Zurich,C=CH", generate_keypair())
        broker = DurableQueueBroker()
        server = SecureBrokerServer(
            broker, host_ident.certificate, host_ident.keypair.private,
            host_ident.trust_root,
        )
        port = server.address[1]
        try:
            ident, fab = _fabric_client(server.address, "Reconnector")
            fab.publish("rq", b"before")
            m = fab.consume("rq", timeout=1.0)
            assert m.payload == b"before"
            fab.ack(m.msg_id)

            # restart the server on the SAME port (fresh broker store —
            # the durable state normally lives in the sqlite file)
            server.close()
            broker.close()
            broker = DurableQueueBroker()
            server = SecureBrokerServer(
                broker, host_ident.certificate, host_ident.keypair.private,
                host_ident.trust_root, port=port,
            )
            # control lane: publish re-handshakes and lands
            fab.publish("rq", b"after")
            assert broker.depth("rq") == 1
            # consumer lane: first poll absorbs the dead channel, a later
            # poll delivers
            deadline = time.monotonic() + 10
            got = None
            while got is None and time.monotonic() < deadline:
                got = fab.consume("rq", timeout=0.5)
            assert got is not None and got.payload == b"after"
            fab.ack(got.msg_id)
            fab.close()
        finally:
            server.close()
            broker.close()

    def test_consume_gives_up_on_permanently_dead_broker(self):
        """Reconnect is BOUNDED: past the retry budget the error
        propagates so consumer loops exit instead of polling a dead
        address forever."""
        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        ident, fab = _fabric_client(server.address, "Bounded")
        fab._reconnect_attempts = 2
        server.close()
        broker.close()
        polls = 0
        with pytest.raises((ConnectionError, OSError)):
            for _ in range(50):
                polls += 1
                fab.consume("q", timeout=0.05)
        assert polls <= 4  # budget + the failing poll, not 50
        fab.close()

    def test_concurrent_consumers_get_own_channels(self):
        import threading

        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        try:
            _, fab = _fabric_client(server.address, "Peer")
            for i in range(8):
                fab.publish("q", f"m{i}".encode())
            got, lock = [], threading.Lock()

            def consume():
                while True:
                    m = fab.consume("q", timeout=0.3)
                    if m is None:
                        return
                    fab.ack(m.msg_id)
                    with lock:
                        got.append(m.payload)

            threads = [threading.Thread(target=consume) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert sorted(got) == [f"m{i}".encode() for i in range(8)]
            fab.close()
        finally:
            server.close()
            broker.close()


class TestSecureEnsembleInProcess:
    """A full node ensemble (notary + two parties) whose only transport is
    the authenticated fabric — flows, notarisation and vault updates all
    cross it."""

    def test_notarised_payment_over_secure_fabric(self):
        from corda_tpu.node.config import NodeConfiguration, NotaryConfig, VerifierType
        from corda_tpu.node.network_map import NetworkMapCache
        from corda_tpu.node.node import Node

        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        clients, nodes = [], []
        try:
            shared_map = NetworkMapCache()

            def start_node(org, notary=False):
                name = f"O={org},L=London,C=GB"
                canonical = str(CordaX500Name.parse(name))
                ident, fab = _fabric_client(server.address, org)
                clients.append(fab)
                messaging = BrokerMessagingClient(fab, canonical)
                cfg = NodeConfiguration(
                    my_legal_name=name,
                    notary=NotaryConfig(validating=True) if notary else None,
                    verifier_type=VerifierType.InMemory,
                    cordapp_packages=("corda_tpu.finance",),
                )
                node = Node(
                    cfg, messaging, network_map=shared_map,
                    keypair=ident.keypair,
                ).start()
                nodes.append(node)
                return node

            notary = start_node("Notary", notary=True)
            alice = start_node("Alice")
            bob = start_node("Bob")

            res = alice.run_flow(
                CashIssueFlow(100, "GBP", b"\x01", notary.party), timeout=30
            )
            assert res is not None
            bob_vault = bob.services.vault_service
            alice.run_flow(
                CashPaymentFlow(40, "GBP", bob.party), timeout=30
            )
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(bob_vault.unconsumed_states()) >= 1:
                    break
                time.sleep(0.05)
            assert len(bob_vault.unconsumed_states()) >= 1
        finally:
            for n in nodes:
                n.stop()
            for c in clients:
                c.close()
            server.close()
            broker.close()


class TestSecureVerifierWorker:
    def test_out_of_process_verifier_over_fabric(self):
        """The verifier worker connects to the node's broker as a certified
        peer (reference: Verifier.kt:49-66 opens a TLS Artemis connection
        to the node) and serves verification requests across it."""
        from corda_tpu.testing import GeneratedLedger
        from corda_tpu.verifier.worker import (
            OutOfProcessVerifierService, VerifierWorker,
        )

        broker = DurableQueueBroker()
        _, server = _fabric_server(broker)
        try:
            node_ident, node_fab = _fabric_client(server.address, "NodeSide")
            _, worker_fab = _fabric_client(server.address, "WorkerSide")
            # the response queue is addressed to the node's CHANNEL
            # identity — the broker authorizes its consumption by name
            svc = OutOfProcessVerifierService(
                node_fab, str(node_ident.party.name)
            )
            worker = VerifierWorker(worker_fab, use_device=False).start()
            gen = GeneratedLedger(seed=7)
            txs = list(gen.generate(4, with_notary_sig=True).values())

            def resolver(ref):
                return gen.transactions[ref.txhash].tx.outputs[ref.index]

            futures = [svc.verify_stx(stx, resolver) for stx in txs]
            for f in futures:
                assert f.result(timeout=30) is None
            # the worker bumps its counter after replying — the futures can
            # resolve a beat earlier over a real wire
            deadline = time.monotonic() + 5
            while worker.verified < len(txs) and time.monotonic() < deadline:
                time.sleep(0.02)
            assert worker.verified == len(txs)
            worker.stop()
            svc.shutdown()
        finally:
            server.close()
            broker.close()


class TestProductionModeFabric:
    def test_operator_provisioned_certs_boot_the_fabric(self, tmp_path):
        """devMode=false: nodes refuse to self-provision — the operator
        places identity.cbe/truststore.cbe issued by the REAL network CA,
        and the ensemble boots over the authenticated fabric with no dev
        CA anywhere in the chain."""
        from corda_tpu.node.certificates import NodeIdentity
        from corda_tpu.node.config import NodeConfiguration
        from corda_tpu.node.startup import build_node

        network_ca = generate_keypair()  # the real operator root

        def provision(org):
            name = f"O={org},L=London,C=GB"
            base = tmp_path / org
            ident = issue_identity(name, generate_keypair(), ca=network_ca)
            save_identity(base / "certificates", ident)
            return name, base

        host_name, host_base = provision("FabricHost")
        peer_name, peer_base = provision("PeerNode")
        host_canonical = str(CordaX500Name.parse(host_name))

        host = build_node(
            NodeConfiguration(
                my_legal_name=host_name, base_directory=str(host_base),
                dev_mode=False,
            ),
            str(tmp_path / "host-broker.db"),
            is_network_map=True, fabric_listen="127.0.0.1:0",
        )
        try:
            addr = f"{host.fabric_server.address[0]}:{host.fabric_server.address[1]}"
            peer = build_node(
                NodeConfiguration(
                    my_legal_name=peer_name, base_directory=str(peer_base),
                    dev_mode=False, network_map_address=host_canonical,
                ),
                ":memory:", fabric_address=addr,
            )
            try:
                # the peer registered with the host's network map over the
                # authenticated channel
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if len(host.services.network_map_cache.all_nodes()) >= 2:
                        break
                    time.sleep(0.05)
                assert len(host.services.network_map_cache.all_nodes()) >= 2
                # a DEV-CA identity is an outsider on this network
                dev_ident = issue_identity(
                    "O=DevPeer,L=London,C=GB", generate_keypair()
                )
                with pytest.raises((HandshakeError, ConnectionError)):
                    fab = SecureFabricClient(
                        addr, dev_ident.certificate,
                        dev_ident.keypair.private, network_ca.public,
                    )
                    fab.publish("q", b"x")
                # the peer authenticated the HOST's identity too (mutual)
                assert str(peer.fabric_client.peer.party.name) == host_canonical
            finally:
                peer.stop()
        finally:
            host.stop()


@pytest.mark.slow
class TestSecureDriverEnsemble:
    """Real node subprocesses over the authenticated TCP fabric — the
    driver-tier proof that the secure transport IS the node fabric."""

    def test_payment_and_rogue_refusal_over_secure_fabric(self, tmp_path):
        with driver(str(tmp_path), secure=True) as dsl:
            dsl.start_node("O=Notary,L=Zurich,C=CH", notary=True)
            alice = dsl.start_node("O=Alice,L=London,C=GB")
            bob = dsl.start_node("O=Bob,L=Rome,C=IT")
            conn = dsl.rpc(alice)
            deadline = time.monotonic() + 30
            notaries = []
            while time.monotonic() < deadline:
                notaries = conn.proxy.notary_identities()
                if notaries and len(conn.proxy.network_map_snapshot()) >= 3:
                    break
                time.sleep(0.3)
            assert len(notaries) == 1
            fid = conn.proxy.start_flow_dynamic(
                class_path(CashIssueFlow), 100, "GBP", b"\x01", notaries[0]
            )
            conn.proxy.flow_result(fid, 60)
            bob_party = conn.proxy.well_known_party_from_x500_name(
                CordaX500Name.parse("O=Bob,L=Rome,C=IT")
            )
            fid = conn.proxy.start_flow_dynamic(
                class_path(CashPaymentFlow), 40, "GBP", bob_party
            )
            conn.proxy.flow_result(fid, 90)
            bconn = dsl.rpc(bob)
            assert bconn.proxy.vault_query_by().total_states_available == 1

            # an uncertified peer cannot even open the fabric
            rogue_ca = generate_keypair()
            ident = issue_identity(
                "O=Rogue,L=Nowhere,C=GB", generate_keypair(), ca=rogue_ca
            )
            with pytest.raises((HandshakeError, ConnectionError)):
                fab = SecureFabricClient(
                    dsl.fabric_address, ident.certificate,
                    ident.keypair.private, dev_trust_root().public,
                )
                fab.publish("p2p.O=Alice, L=London, C=GB", b"forged")
