"""Concurrency-observatory tests — lock-contention timing (per-site
acquire-wait/hold reservoirs, the top-contended table, holder→waiter
wait edges), the timed-lock wrapper's Condition composition, the
factory install/uninstall hook, the sampler classifier's wait-site
registry and frame walk, the Prometheus/timeline/flight-dump surfaces,
and the acceptance pin: with ``CORDA_TPU_CONTENTION`` unset there is NO
patched factory, NO extra thread and ZERO ``contention.*`` metrics
(fresh subprocess)."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from corda_tpu.observability.contention import (
    MAX_SITES,
    OVERFLOW_SITE,
    ContentionMonitor,
    TimedContentionLock,
    _Reservoir,
    classify_frame,
    configure_contention,
    contention_section,
    install,
    installed,
    register_wait_site,
    timed_lock,
    uninstall,
    wrap_lock,
)
from corda_tpu.observability.exposition import parse_prometheus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def mon():
    return ContentionMonitor()


def _convoy(lock, hold_s=0.05):
    """Grab ``lock`` on a helper thread and hold it while the caller
    blocks on acquire — one deterministic contended acquire."""
    taken = threading.Event()

    def holder():
        with lock:
            taken.set()
            time.sleep(hold_s)

    t = threading.Thread(target=holder, name="convoy-holder")
    t.start()
    taken.wait(timeout=5.0)
    with lock:
        pass
    t.join(timeout=5.0)


# ----------------------------------------------------------- reservoir

class TestReservoir:
    def test_quantiles_monotone_and_bounded(self):
        r = _Reservoir(slots=64)
        for i in range(1000):
            r.add(float(i))
        q = r.quantiles()
        assert 0.0 <= q["p50"] <= q["p95"] <= q["p99"] <= 999.0
        assert len(r._buf) == 64          # memory stays bounded

    def test_empty_reservoir_is_zeroes(self):
        assert _Reservoir().quantiles() == {
            "p50": 0.0, "p95": 0.0, "p99": 0.0,
        }


# ----------------------------------------------------------- the ledger

class TestTimedContentionLock:
    def test_uncontended_acquire_counts_but_is_not_contended(self, mon):
        lk = TimedContentionLock("t.site", _monitor=mon)
        with lk:
            pass
        snap = mon.snapshot()
        s = snap["sites"]["t.site"]
        assert s["acquires"] == 1
        assert s["contended"] == 0
        assert s["wait_total_s"] == 0.0
        # the uncontended site never reaches the top-contended table
        assert snap["top"] == []

    def test_convoy_books_wait_and_edge(self, mon):
        lk = TimedContentionLock("t.convoy", _monitor=mon)
        _convoy(lk, hold_s=0.05)
        snap = mon.snapshot()
        s = snap["sites"]["t.convoy"]
        assert s["acquires"] == 2
        assert s["contended"] >= 1
        assert s["wait_total_s"] >= 0.03
        assert s["wait_p50_s"] <= s["wait_p95_s"] <= s["wait_p99_s"]
        assert s["hold_p50_s"] <= s["hold_p95_s"] <= s["hold_p99_s"]
        # the holder's ~0.05s hold made it into the hold reservoir
        assert s["hold_p99_s"] >= 0.03
        assert [r["site"] for r in snap["top"]] == ["t.convoy"]
        # the blocked main thread held no timed lock → thread-name waiter
        (edge,) = snap["edges"]
        assert edge["holder"] == "t.convoy"
        assert edge["waiter"] == "thread:MainThread"
        assert edge["count"] == 1
        assert edge["wait_s"] >= 0.03

    def test_edge_waiter_is_innermost_held_timed_lock(self, mon):
        """A thread that blocks while holding another timed lock names
        THAT site as the waiter — the 'A convoys behind B' arrow."""
        outer = TimedContentionLock("t.outer", _monitor=mon)
        inner = TimedContentionLock("t.inner", _monitor=mon)
        taken = threading.Event()

        def holder():
            with inner:
                taken.set()
                time.sleep(0.05)

        t = threading.Thread(target=holder)
        t.start()
        taken.wait(timeout=5.0)
        with outer:          # held while blocking on inner
            with inner:
                pass
        t.join(timeout=5.0)
        edges = {(e["holder"], e["waiter"]) for e in mon.snapshot()["edges"]}
        assert ("t.inner", "t.outer") in edges

    def test_reentrant_hold_timed_on_outermost_release(self, mon):
        lk = TimedContentionLock("t.re", _monitor=mon, reentrant=True)
        with lk:
            with lk:
                time.sleep(0.02)
        s = mon.snapshot()["sites"]["t.re"]
        assert s["acquires"] == 2
        # the outermost release books the real hold; the inner one a 0
        assert s["hold_p99_s"] >= 0.015

    def test_failed_try_acquire_counts_as_blocked(self, mon):
        lk = TimedContentionLock("t.try", _monitor=mon)
        taken = threading.Event()
        release = threading.Event()

        def holder():
            with lk:
                taken.set()
                release.wait(5.0)

        t = threading.Thread(target=holder)
        t.start()
        taken.wait(timeout=5.0)
        assert lk.acquire(blocking=False) is False
        release.set()
        t.join(timeout=5.0)
        (edge,) = mon.snapshot()["edges"]
        assert edge["holder"] == "t.try" and edge["count"] == 1

    def test_condition_composition_wait_notify(self, mon):
        """The SMM idiom: a Condition over a wrapped reentrant lock —
        wait/notify must work through _release_save/_acquire_restore,
        and the roundtrip feeds the site's ledger."""
        cv = threading.Condition(
            TimedContentionLock("t.cv", _monitor=mon, reentrant=True)
        )
        state = {"go": False}

        def waiter():
            with cv:
                cv.wait_for(lambda: state["go"], timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with cv:
            state["go"] = True
            cv.notify_all()
        t.join(timeout=5.0)
        assert not t.is_alive()
        s = mon.snapshot()["sites"]["t.cv"]
        # entry acquires on both threads + the waiter's wait() reacquire
        assert s["acquires"] >= 3

    def test_wrap_lock_composes_over_foreign_lock(self, mon):
        inner = threading.RLock()
        lk = TimedContentionLock("t.wrap", _monitor=mon, _inner=inner)
        with lk:
            assert lk._is_owned()
        assert mon.snapshot()["sites"]["t.wrap"]["acquires"] == 1

    def test_overflow_pools_excess_sites(self, mon):
        for i in range(MAX_SITES + 10):
            mon.note_acquire(f"site-{i}", 0.0, contended=False)
            mon.note_release(f"site-{i}", 0.0)
        sites = mon.snapshot()["sites"]
        assert len(sites) <= MAX_SITES + 1
        assert OVERFLOW_SITE in sites
        assert sites[OVERFLOW_SITE]["acquires"] == 10


# ------------------------------------------------------- factory patch

class TestInstall:
    def test_install_patches_and_uninstall_restores(self):
        real_lock = threading.Lock
        try:
            install()
            assert installed()
            lk = threading.Lock()
            assert isinstance(lk, TimedContentionLock)
            rlk = threading.RLock()
            assert isinstance(rlk, TimedContentionLock)
            with rlk:
                with rlk:     # reentrant through the patch
                    pass
            cv = threading.Condition()
            assert isinstance(cv._lock, TimedContentionLock)
            with cv:
                cv.notify_all()
        finally:
            uninstall()
        assert not installed()
        assert threading.Lock is real_lock
        assert not isinstance(threading.Lock(), TimedContentionLock)

    def test_timed_lock_names_allocation_site(self):
        lk = timed_lock()     # no explicit name → file:line site
        assert "test_contention.py" in lk.name
        assert timed_lock("explicit").name == "explicit"
        assert wrap_lock(threading.RLock(), "w").name == "w"


# ------------------------------------------------- the frame classifier

class _FakeCode:
    def __init__(self, filename, name):
        self.co_filename = filename
        self.co_name = name


class _FakeFrame:
    def __init__(self, filename, name, back=None):
        self.f_code = _FakeCode(filename, name)
        self.f_back = back


class TestClassifyFrame:
    def test_stdlib_wait_sites(self):
        f = _FakeFrame("/usr/lib/python3.11/threading.py", "wait")
        assert classify_frame(f) == "lock_wait"
        f = _FakeFrame("/usr/lib/python3.11/selectors.py", "select")
        assert classify_frame(f) == "io_wait"

    def test_runnable_frame_is_none(self):
        f = _FakeFrame("/repo/corda_tpu/flows/engine.py", "run")
        assert classify_frame(f) is None

    def test_registered_site_wins_over_stdlib(self):
        """A WAL flush blocked in cv.wait is io-wait: the stdlib frame
        says THAT the thread waits, the subsystem frame says WHY."""
        register_wait_site("fakewal.py", "flush", "io_wait")
        inner = _FakeFrame("/usr/lib/python3.11/threading.py", "wait")
        outer = _FakeFrame("/repo/fakewal.py", "flush")
        inner.f_back = outer
        assert classify_frame(inner) == "io_wait"

    def test_max_depth_bounds_the_walk(self):
        # the wait frame sits 20 call levels below the innermost frame —
        # outside the 16-frame walk window, so the thread reads runnable
        frame = _FakeFrame("/usr/lib/python3.11/threading.py", "wait")
        for i in range(20):
            frame = _FakeFrame("/repo/app.py", f"fn{i}", back=frame)
        assert classify_frame(frame, max_depth=16) is None
        assert classify_frame(frame, max_depth=32) == "lock_wait"

    def test_register_rejects_unknown_cause(self):
        with pytest.raises(ValueError):
            register_wait_site("x.py", "f", "napping")

    def test_subsystems_registered_their_wait_sites(self):
        """Importing the WAL and the engine registers their wait sites
        (the classifier's subsystem table is populated at import)."""
        import corda_tpu.durability.wal  # noqa: F401
        import corda_tpu.flows.engine  # noqa: F401
        from corda_tpu.observability.contention import wait_sites

        sites = wait_sites()
        assert sites[("wal.py", "flush")] == "io_wait"
        assert sites[("engine.py", "_worker_loop")] == "lock_wait"


# ---------------------------------------------------- process surfaces

class TestSurfaces:
    def test_section_disabled_marker(self):
        configure_contention(enabled=False, patch=False)
        assert contention_section() == {"enabled": False}

    def test_section_and_prometheus_while_on(self):
        configure_contention(enabled=True, patch=False, reset=True)
        try:
            lk = timed_lock('hostile"site\\name')
            _convoy(lk, hold_s=0.03)
            sec = contention_section()
            assert sec["enabled"] and sec["schema"] == 1
            assert 'hostile"site\\name' in sec["sites"]
            from corda_tpu.observability.contention import (
                prometheus_lines,
            )

            text = "\n".join(prometheus_lines()) + "\n"
            samples = parse_prometheus(text)   # raises on malformed lines
            assert any(
                "contention_site_acquires_total" in k for k in samples
            )
            assert any(
                "contention_wait_edge_total" in k for k in samples
            )
            # the registry gained the contention.* names
            from corda_tpu.node.monitoring import node_metrics

            names = list(node_metrics().snapshot())
            assert "contention.acquires" in names
            assert "contention.wait_s" in names
        finally:
            configure_contention(enabled=False, patch=False, reset=True)

    def test_registry_snapshot_completes_with_patched_metric_locks(self):
        """Deadlock pin: registry.snapshot() holds the registry lock
        while acquiring every metric's own lock — metrics born under the
        factory patch have TIMED guards, and a note path that looked
        contention.* metrics up by name would re-enter the registry lock
        (same thread) or ABBA a concurrent writer. The note paths must
        run off the cached metric objects."""
        from corda_tpu.node.monitoring import node_metrics
        from corda_tpu.observability.contention import (
            configure_contention,
        )

        configure_contention(enabled=True, patch=True, reset=True)
        try:
            # a metric born under the patch: its guard lock is timed
            t = node_metrics().timer("contention_test.patched_timer")
            t.update(0.001)
            done = threading.Event()

            def snap():
                node_metrics().snapshot()
                from corda_tpu.node.monitoring import monitoring_snapshot
                monitoring_snapshot()
                done.set()

            th = threading.Thread(target=snap, daemon=True)
            th.start()
            assert done.wait(timeout=30), (
                "registry snapshot deadlocked against the contention "
                "note paths"
            )
        finally:
            configure_contention(enabled=False, patch=True, reset=True)
            with node_metrics()._lock:
                node_metrics()._metrics.pop(
                    "contention_test.patched_timer", None)

    def test_env_probe_runs_at_import_fresh_subprocess(self):
        """CORDA_TPU_CONTENTION=1 must be live from the observability
        import itself — a dump-and-exit tool that never constructs an
        SMM (never hits the active_contention() hot-path check) still
        reads an enabled section."""
        code = """
import json
import corda_tpu.observability  # the env probe runs at import
from corda_tpu.node.monitoring import monitoring_snapshot
from corda_tpu.observability.contention import installed
sec = monitoring_snapshot()["contention"]
assert sec["enabled"], sec
assert sec["installed"] and installed()
print(json.dumps({"ok": True}))
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "CORDA_TPU_CONTENTION": "1",
                 "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout.strip().splitlines()[-1])["ok"]

    def test_timeline_tap_renders_contention_series(self):
        """Satellite: the timeline's default allowlists tap the
        contention families — a convoy between ticks lands as
        ``contention.*`` series in the snapshot."""
        from corda_tpu.observability import configure_timeline
        from corda_tpu.observability.timeseries import timeline

        configure_contention(enabled=True, patch=False, reset=True)
        configure_timeline(enabled=True, cadence_s=0.05, ring_points=16,
                           thread=False, reset=True)
        try:
            tl = timeline()
            tl.tick()
            _convoy(timed_lock("tap.site"), hold_s=0.03)
            tl.tick()
            series = tl.snapshot()["series"]
            assert "contention.acquires" in series
            assert series["contention.acquires"]["points"][-1] >= 2.0
            assert "contention.wait_s.p50_s" in series
        finally:
            configure_timeline(enabled=False, reset=True)
            configure_contention(enabled=False, patch=False, reset=True)

    def test_flight_dump_round_trips_contention_and_causal(self, tmp_path):
        from corda_tpu.node.monitoring import node_metrics
        from corda_tpu.observability.slo import (
            flight_dump,
            read_flight_dump,
        )

        # flight_dump incs slo.flight_dumps — scrub any slo.* metric this
        # test births so the devicemon off-by-default pin (which asserts
        # an slo.*-free exposition, and sorts after this file) stays true
        reg = node_metrics()
        before = set(reg.snapshot())
        try:
            configure_contention(enabled=False, patch=False)
            path = flight_dump(str(tmp_path / "off.jsonl"), reason="off")
            out = read_flight_dump(path)
            assert out["contention"] == {"enabled": False}
            assert out["causal"] == {"enabled": False} or \
                out["causal"].get("enabled")

            configure_contention(enabled=True, patch=False, reset=True)
            try:
                _convoy(timed_lock("dump.site"), hold_s=0.03)
                path = flight_dump(str(tmp_path / "on.jsonl"), reason="on")
                out = read_flight_dump(path)
                assert out["contention"]["enabled"]
                assert "dump.site" in out["contention"]["sites"]
                json.dumps(out["contention"])   # JSON all the way down
            finally:
                configure_contention(enabled=False, patch=False, reset=True)
        finally:
            with reg._lock:
                for name in set(reg._metrics) - before:
                    if name.startswith("slo."):
                        del reg._metrics[name]

    def test_rpc_bindings_wrap_the_sections(self):
        from corda_tpu.rpc.bindings import (
            contention_snapshot_value,
            speedup_ledger_value,
        )

        class FakeProxy:
            def contention_snapshot(self, top_n=16):
                return {"enabled": False}

            def speedup_ledger(self):
                return {"enabled": False}

        assert contention_snapshot_value(FakeProxy()).refresh() == {
            "enabled": False,
        }
        assert speedup_ledger_value(FakeProxy()).refresh() == {
            "enabled": False,
        }

    def test_monitoring_snapshot_carries_both_sections(self):
        from corda_tpu.node.monitoring import monitoring_snapshot

        snap = monitoring_snapshot()
        assert "contention" in snap
        assert "causal" in snap


# ------------------------------------------------- off-by-default pins

class TestOffByDefaultPins:
    def test_zero_footprint_when_off_fresh_subprocess(self):
        """The acceptance pin: with CORDA_TPU_CONTENTION unset a REAL
        mocknet flow leaves the lock factories untouched, spawns no
        observatory thread, hands back None from the hot-path check and
        registers ZERO contention./causal. metrics — fresh subprocess so
        no other test's configure_* latch can mask a regression."""
        code = """
import json, os, threading
os.environ.pop("CORDA_TPU_CONTENTION", None)
real_lock = threading.Lock
from corda_tpu.finance import CashIssueFlow
from corda_tpu.testing import MockNetworkNodes
from corda_tpu.node.monitoring import monitoring_snapshot, node_metrics
from corda_tpu.observability.contention import (
    active_contention, installed,
)
with MockNetworkNodes() as net:
    alice = net.create_node("OffAlice")
    notary = net.create_notary_node("OffNotary")
    alice.run_flow(CashIssueFlow(100, "GBP", b"\\x01", notary.party))
snap = monitoring_snapshot()
assert snap["contention"] == {"enabled": False}, snap["contention"]
assert snap["causal"] == {"enabled": False}, snap["causal"]
names = list(node_metrics().snapshot())
assert not any(
    n.startswith(("contention.", "causal.")) for n in names
), names
assert active_contention() is None
assert not installed()
assert threading.Lock is real_lock
print(json.dumps({"ok": True}))
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout.strip().splitlines()[-1])["ok"]

    def test_env_knob_times_the_smm_monitor_fresh_subprocess(self):
        """CORDA_TPU_CONTENTION=1: the env probe installs the factory
        patch, the engine wraps its SMM lock under the stable
        ``engine.smm`` site, and a real flow's section carries it."""
        code = """
import json, threading
from corda_tpu.observability.contention import (
    active_contention, installed,
)
assert active_contention() is not None      # env probe enables
assert installed()
from corda_tpu.observability.contention import TimedContentionLock
assert isinstance(threading.Lock(), TimedContentionLock)
from corda_tpu.finance import CashIssueFlow
from corda_tpu.testing import MockNetworkNodes
from corda_tpu.node.monitoring import monitoring_snapshot
with MockNetworkNodes() as net:
    alice = net.create_node("EnvAlice")
    notary = net.create_notary_node("EnvNotary")
    alice.run_flow(CashIssueFlow(100, "GBP", b"\\x01", notary.party))
snap = monitoring_snapshot()["contention"]
assert snap["enabled"] and snap["installed"]
assert "engine.smm" in snap["sites"], sorted(snap["sites"])[:20]
assert snap["sites"]["engine.smm"]["acquires"] > 0
print(json.dumps({"ok": True}))
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=180,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "CORDA_TPU_CONTENTION": "1",
                 "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout.strip().splitlines()[-1])["ok"]
