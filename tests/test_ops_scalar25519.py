"""Differential tests for the on-device challenge pipeline: SHA-512 digest
words → little-endian 512-bit limbs → Barrett mod-L → ladder windows,
against Python bigints and hashlib (the host oracle the v1 pipeline used
per-lane — reference path Crypto.kt:621-624's JCA EdDSA engine does the
same reduction inside `Signature.verify`)."""

import hashlib
import random

import numpy as np

from corda_tpu.ops import scalar25519 as sc


def _limbs43(x: int) -> np.ndarray:
    return np.array(
        [(x >> (12 * i)) & 0xFFF for i in range(43)], dtype=np.int32
    )


class TestModL:
    def test_barrett_matches_bigint(self):
        rng = random.Random(1)
        vals = [
            0, 1, sc.L - 1, sc.L, sc.L + 1, 2 * sc.L, (1 << 512) - 1,
            (sc.L << 260) - 1,
        ] + [rng.getrandbits(512) for _ in range(24)]
        h = np.stack([_limbs43(v) for v in vals]).T  # (43, B)
        r = np.asarray(sc.mod_l(h))
        for i, v in enumerate(vals):
            got = sum(int(r[k, i]) << (12 * k) for k in range(22))
            assert got == v % sc.L, (i, v)

    def test_windows_match_bit_slices(self):
        rng = random.Random(2)
        vals = [rng.getrandbits(512) % sc.L for _ in range(8)]
        r = np.stack(
            [_limbs43(v)[:22] for v in vals]
        ).T.astype(np.int32)
        w = np.asarray(sc.limbs_to_windows(r))
        assert w.shape == (64, 8)
        for i, v in enumerate(vals):
            for k in range(64):
                assert w[k, i] == (v >> (4 * k)) & 0xF

    def test_digest_words_roundtrip(self):
        """hashlib digest → hi/lo word pairs → limbs equals the bigint."""
        msgs = [b"abc", b"", b"x" * 100, b"corda-tpu"]
        words = np.zeros((len(msgs), 16), dtype=np.uint32)
        for i, m in enumerate(msgs):
            d = hashlib.sha512(m).digest()
            for w in range(8):
                v = int.from_bytes(d[8 * w : 8 * w + 8], "big")
                words[i, 2 * w] = v >> 32
                words[i, 2 * w + 1] = v & 0xFFFFFFFF
        limbs = np.asarray(sc.digest_words_to_limbs(words))
        for i, m in enumerate(msgs):
            want = int.from_bytes(hashlib.sha512(m).digest(), "little")
            got = sum(int(limbs[k, i]) << (12 * k) for k in range(43))
            assert got == want

    def test_challenge_windows_end_to_end(self):
        """Full device challenge path vs hashlib + bigint mod L."""
        rng = random.Random(3)
        msgs = [rng.randbytes(108) for _ in range(4)]
        words = np.zeros((4, 16), dtype=np.uint32)
        for i, m in enumerate(msgs):
            d = hashlib.sha512(m).digest()
            for w in range(8):
                v = int.from_bytes(d[8 * w : 8 * w + 8], "big")
                words[i, 2 * w] = v >> 32
                words[i, 2 * w + 1] = v & 0xFFFFFFFF
        wins = np.asarray(sc.challenge_windows(words))
        for i, m in enumerate(msgs):
            h = int.from_bytes(hashlib.sha512(m).digest(), "little") % sc.L
            for k in range(64):
                assert wins[k, i] == (h >> (4 * k)) & 0xF
