"""Cluster observatory tests — network-path telemetry (per-edge
ledgers, first-send transit semantics, the edge-triggered partition
detector), clock-skew correction, distributed trace assembly (one
assembled trace per payment, hop transits reconciling against
flowprof's ``message_transit``), metrics federation (per-node sections
EXACTLY equal to each node's local snapshot), Prometheus label-value
escaping under hostile names, flight-dump forward-compat, and the
off-by-default zero-names pin (fresh subprocess)."""

import json
import os
import subprocess
import sys
import time

import pytest

from corda_tpu.messaging.netstats import (
    NetTelemetry,
    configure_netstats,
    logical_msg_id,
    netstats,
    netstats_section,
)
from corda_tpu.observability.cluster import (
    ClusterRecorder,
    EdgeOffsetEstimator,
    TraceAssembler,
    cluster_section,
    configure_cluster,
)
from corda_tpu.observability.federation import (
    federated_snapshot,
    render_federated_prometheus,
)
from corda_tpu.observability.exposition import (
    escape_label_value,
    parse_prometheus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def nt():
    clock = FakeClock()
    t = NetTelemetry(partition_deadline_s=2.0, clock=clock)
    t.enable()
    t.clock = clock  # test handle
    return t


# --------------------------------------------------- edge ledger (unit)

class TestNetTelemetry:
    def test_logical_id_strips_retransmit_suffix(self):
        assert logical_msg_id("m1") == "m1"
        assert logical_msg_id("m1~3") == "m1"
        assert logical_msg_id("m~1~2") == "m"

    def test_transit_is_first_send_to_delivery(self, nt):
        """A retransmitted message keeps its ORIGINAL stamp: transit
        honestly includes the loss-recovery wall."""
        nt.on_send("a", "b", "m1")
        nt.clock.advance(1.0)
        nt.on_send("a", "b", "m1~1")          # retransmit, stamp kept
        nt.clock.advance(0.5)
        nt.on_deliver("a", "b", "m1~1")
        snap = nt.snapshot()
        e = snap["edges"]["a->b"]
        assert e["delivered"] == 1
        assert e["retransmits"] == 1
        assert e["pending"] == 0
        assert e["transit_p50_s"] == pytest.approx(1.5)

    def test_drop_delay_duplicate_accounting(self, nt):
        nt.on_drop("a", "b", "partition")
        nt.on_drop("a", "b", "down")
        nt.on_drop("a", "b", "partition")
        nt.on_delay("a", "b", 3)
        nt.on_duplicate("a", "b")
        e = nt.snapshot()["edges"]["a->b"]
        assert e["drops"] == 3
        assert e["drops_by_reason"] == {"partition": 2, "down": 1}
        assert e["delays"] == 1 and e["delay_rounds"] == 3
        assert e["duplicates_dropped"] == 1

    def test_partition_fires_exactly_once_per_episode(self, nt):
        """Edge-triggered: ONE suspect event per episode however many
        checks run, cleared by the next delivery (healed event), and a
        fresh episode fires again."""
        nt.on_send("a", "b", "m1")
        assert nt.check_partitions() == []          # within deadline
        nt.clock.advance(3.0)
        fired = nt.check_partitions()
        assert [e["kind"] for e in fired] == ["net.partition_suspect"]
        assert fired[0]["edge"] == "a->b"
        assert fired[0]["waited_s"] == pytest.approx(3.0)
        # re-checks while still suspected stay silent
        nt.clock.advance(10.0)
        assert nt.check_partitions() == []
        assert nt.snapshot()["suspects"] == ["a->b"]
        # delivery heals
        nt.on_deliver("a", "b", "m1")
        snap = nt.snapshot()
        assert snap["suspects"] == []
        kinds = [e["kind"] for e in snap["events"]]
        assert kinds == ["net.partition_suspect", "net.partition_healed"]
        # a second episode fires a second (single) event
        nt.on_send("a", "b", "m2")
        nt.clock.advance(3.0)
        assert len(nt.check_partitions()) == 1
        assert nt.snapshot()["edges"]["a->b"]["episodes"] == 2

    def test_worst_edge_p99_and_total_retransmits(self, nt):
        nt.on_send("a", "b", "m1")
        nt.clock.advance(0.1)
        nt.on_deliver("a", "b", "m1")
        nt.on_send("a", "c", "m2")
        nt.clock.advance(0.4)
        nt.on_deliver("a", "c", "m2")
        nt.on_send("a", "c", "m2~1")
        assert nt.transit_p99_s() == pytest.approx(0.4)
        assert nt.total_retransmits() == 1

    def test_prometheus_lines_parse_with_hostile_edge(self, nt):
        nt.on_send('a"x\\y', "b", "m1")
        nt.clock.advance(0.2)
        nt.on_deliver('a"x\\y', "b", "m1")
        text = "\n".join(nt.prometheus_lines()) + "\n"
        samples = parse_prometheus(text)  # raises on any malformed line
        assert any("net_edge_delivered" in k for k in samples)


# --------------------------------- partition detector through the wire

class TestPartitionIntegration:
    def test_seeded_partition_suspect_once_then_heals(self):
        """A fault-plan partition through the real in-memory transport:
        the drop is attributed ``partition``, the suspect event fires
        ONCE while the pending send ages, and the post-heal retransmit
        delivers, healing the edge with recovery wall in the transit."""
        from corda_tpu.faultinject import FaultInjector, FaultPlan, Partition
        from corda_tpu.messaging.network import InMemoryMessagingNetwork

        plan = FaultPlan(seed=7, partitions=(
            Partition(0, 3, frozenset({"n1"}), frozenset({"n2"})),
        ))
        net = InMemoryMessagingNetwork(fault_injector=FaultInjector(plan))
        n1 = net.create_node("n1")
        n2 = net.create_node("n2")
        n2.add_handler("t", lambda m: None)
        configure_netstats(enabled=True, reset=True,
                           partition_deadline_s=0.05)
        try:
            n1.send("n2", "t", b"x", msg_id="pmsg")   # severed (round 0)
            net.pump()                                # round 1
            time.sleep(0.12)
            net.pump()                                # round 2 → suspect
            net.pump()                                # round 3 → silent
            n1.send("n2", "t", b"x", msg_id="pmsg~1")  # healed window
            net.pump()
            snap = netstats().snapshot()
            e = snap["edges"]["n1->n2"]
            assert e["drops_by_reason"] == {"partition": 1}
            assert e["retransmits"] == 1
            assert e["delivered"] == 1
            assert e["episodes"] == 1
            assert not e["partition_suspect"]
            kinds = [ev["kind"] for ev in snap["events"]]
            assert kinds.count("net.partition_suspect") == 1
            assert kinds.count("net.partition_healed") == 1
            # transit includes the partition's recovery wall
            assert e["transit_p50_s"] >= 0.12
        finally:
            configure_netstats(enabled=False, reset=True,
                               partition_deadline_s=2.0)


# ------------------------------------------------- clock-skew correction

class TestEdgeOffsetEstimator:
    def _hops(self, skew):
        """Symmetric 0.010s true transit, B's clock ``skew`` ahead."""
        hops = []
        for i, true_t in enumerate((0.010, 0.014, 0.011)):
            t0 = 100.0 + i
            hops.append({"src": "A", "dst": "B", "msg_id": f"f{i}",
                         "t_send": t0, "t_recv": t0 + true_t + skew,
                         "kind": "data", "trace_id": "t"})
            hops.append({"src": "B", "dst": "A", "msg_id": f"r{i}",
                         "t_send": t0 + skew, "t_recv": t0 + true_t,
                         "kind": "data", "trace_id": "t"})
        return hops

    def test_recovers_offset_from_bidirectional_minima(self):
        est = EdgeOffsetEstimator(self._hops(skew=5.0))
        assert est.offset_s("A", "B") == pytest.approx(5.0)
        assert est.offset_s("B", "A") == pytest.approx(-5.0)

    def test_corrected_transit_is_sane_under_skew(self):
        hops = self._hops(skew=5.0)
        est = EdgeOffsetEstimator(hops)
        for h in hops:
            corrected = est.corrected_transit_s(h)
            assert 0.0 <= corrected <= 0.02, (h, corrected)

    def test_one_directional_edge_estimates_zero(self):
        hops = [{"src": "A", "dst": "B", "t_send": 1.0, "t_recv": 7.0}]
        est = EdgeOffsetEstimator(hops)
        assert est.offset_s("A", "B") == 0.0
        assert est.corrected_transit_s(hops[0]) == pytest.approx(6.0)


# ------------------------------------------- hop recorder (unit)

class TestClusterRecorder:
    def test_first_send_stamp_wins_and_join(self):
        rec = ClusterRecorder()
        rec.enable()
        rec.note_send("a", "b", "data", "m1", "tid", now=10.0)
        rec.note_send("a", "b", "data", "m1", "tid", now=11.0)  # retx
        rec.note_recv("b", "a", "m1", "tid", now=10.3)
        (hop,) = rec.hops()
        assert hop["t_send"] == 10.0 and hop["t_recv"] == 10.3
        assert hop["src"] == "a" and hop["dst"] == "b"
        assert rec.hops_for("tid") == [hop]
        assert rec.hops_for("other") == []

    def test_recv_without_send_evidence_is_dropped(self):
        rec = ClusterRecorder()
        rec.enable()
        rec.note_recv("b", "a", "ghost", "tid", now=1.0)
        assert rec.hops() == []
        assert rec.snapshot()["hops"] == 0

    def test_receiver_trace_id_is_authoritative(self):
        rec = ClusterRecorder()
        rec.enable()
        rec.note_send("a", "b", "init", "m1", "send-tid", now=1.0)
        rec.note_recv("b", "a", "m1", "recv-tid", now=1.1)
        rec.note_send("a", "b", "init", "m2", "send-tid", now=2.0)
        rec.note_recv("b", "a", "m2", "", now=2.1)   # unsampled receiver
        tids = [h["trace_id"] for h in rec.hops()]
        assert tids == ["recv-tid", "send-tid"]


# ----------------------------------- distributed assembly (integration)

def _quiesce_monitoring(timeout_s=30.0):
    """Wait until two consecutive monitoring snapshots are equal —
    responder flows (FinalityFlow broadcast) may still be closing after
    the initiator's result resolves."""
    from corda_tpu.node.monitoring import monitoring_snapshot

    prev, deadline = None, time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        cur = monitoring_snapshot()
        if cur == prev:
            return cur
        prev = cur
        time.sleep(0.05)
    raise AssertionError("monitoring snapshot never quiesced")


class TestDistributedAssembly:
    def test_payment_assembles_one_trace_and_reconciles_flowprof(self):
        """The acceptance path: a 3-node notarised payment assembles
        into ONE distributed trace — every span carries the same trace
        id, ≥2 hops crossed the wire, hop transit quantiles are
        monotone, the summed raw data-hop transits match flowprof's
        ``message_transit`` total within 5%, and the critical path
        names a bound-by contributor."""
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
        from corda_tpu.observability import configure_tracing, tracer
        from corda_tpu.observability.flowprof import (
            configure_flowprof, flowprof,
        )
        from corda_tpu.testing import MockNetworkNodes
        from bench import wait_for_complete_trace

        configure_tracing(sample_rate=1.0)
        configure_flowprof(enabled=True, reset=True)
        configure_cluster(enabled=True, reset=True)
        configure_netstats(enabled=True, reset=True)
        try:
            with MockNetworkNodes() as net:
                alice = net.create_node("ClusAlice")
                bob = net.create_node("ClusBob")
                notary = net.create_notary_node("ClusNotary")
                alice.run_flow(
                    CashIssueFlow(500, "GBP", b"\x05", notary.party)
                )
                handle = alice.smm.start_flow(
                    CashPaymentFlow(120, "GBP", bob.party)
                )
                handle.result.result(timeout=120)
                wait_for_complete_trace(
                    tracer(), handle.flow_id,
                    {"flow", "flow.responder", "flow.verify_stx",
                     "notary.attest"},
                )
                _quiesce_monitoring()
                trace = TraceAssembler(net).assemble(
                    flow_id=handle.flow_id
                )

            assert trace["trace_id"]
            # ONE trace: every non-linked span shares the id
            own = [s for s in trace["spans"]
                   if s["trace_id"] == trace["trace_id"]]
            assert own, trace["spans"]
            assert len(trace["nodes"]) >= 2, trace["nodes"]
            hops = trace["hops"]
            assert trace["transit"]["count"] >= 2, trace["transit"]
            assert all(h["name"] == "net.transit" for h in hops)
            assert trace["transit"]["p99_s"] >= trace["transit"]["p50_s"]

            # ±5%: summed raw data-hop transit vs flowprof's
            # message_transit over the trace's flows (the hooks stamp
            # the same engine sites)
            fids = {s["attrs"]["flow.id"] for s in own
                    if s.get("attrs", {}).get("flow.id")}
            fp = flowprof()
            transit_total = 0.0
            for fid in fids:
                wf = fp.waterfall_of(fid)
                if wf is not None:
                    transit_total += wf["phases"].get(
                        "message_transit", 0.0)
            hop_total = sum(
                h["attrs"]["net.raw_s"] for h in hops
                if h["attrs"]["kind"] == "data"
            )
            assert transit_total > 0.0
            assert abs(hop_total - transit_total) <= \
                0.05 * transit_total, (hop_total, transit_total)

            cp = trace["critical_path"]
            assert cp is not None
            assert cp["end_to_end_s"] > 0.0
            assert cp["bound_by"] is not None
            assert cp["bound_by"]["node"], cp["bound_by"]
            # every hop is individually attributed as a remote entry
            assert any(c["kind"] == "hop" for c in cp["contributors"])
        finally:
            configure_netstats(enabled=False, reset=True)
            configure_cluster(enabled=False, reset=True)
            configure_flowprof(enabled=False, reset=True)
            configure_tracing(sample_rate=0.0)

    def test_assemble_needs_a_selector(self):
        with pytest.raises(ValueError):
            TraceAssembler({}, recorder=ClusterRecorder()).assemble()

    def test_unknown_flow_id_yields_empty_trace(self):
        trace = TraceAssembler(
            {"n1": []}, recorder=ClusterRecorder()
        ).assemble(flow_id="nope")
        assert trace["trace_id"] is None
        assert trace["spans"] == [] and trace["hops"] == []
        assert trace["critical_path"] is None

    def test_handle_shapes_span_list_and_callable(self):
        span = {"trace_id": "t1", "span_id": "s1", "parent_id": None,
                "name": "flow", "start_s": 1.0, "end_s": 2.0,
                "duration_s": 1.0, "attrs": {"node": "n1"}, "links": []}
        rec = ClusterRecorder()
        rec.enable()
        for handle in ({"n1": [span]}, {"n1": lambda: [span]}):
            trace = TraceAssembler(handle, recorder=rec).assemble("t1")
            assert [s["span_id"] for s in trace["spans"]] == ["s1"]
            assert trace["nodes"] == ["n1"]
        with pytest.raises(TypeError):
            TraceAssembler(42).assemble("t1")


# ------------------------------------------------------------ federation

class TestFederation:
    def test_per_node_sections_reconcile_exactly(self):
        """The acceptance pin: each node's federation section equals its
        OWN local monitoring_snapshot() — federation relays, never
        recomputes."""
        from corda_tpu.finance import CashIssueFlow
        from corda_tpu.node.monitoring import monitoring_snapshot
        from corda_tpu.testing import MockNetworkNodes

        with MockNetworkNodes() as net:
            alice = net.create_node("FedAlice")
            notary = net.create_notary_node("FedNotary")
            alice.run_flow(CashIssueFlow(100, "GBP", b"\x02", notary.party))
            _quiesce_monitoring()
            doc = federated_snapshot(net)
            assert doc["schema"] == 1
            assert doc["rollup"]["n_nodes"] == 2
            for name, node in net.nodes.items():
                expect = monitoring_snapshot()
                expect["node"] = node.services.metrics.snapshot()
                assert doc["nodes"][name]["snapshot"] == expect, name

    def test_single_node_document_without_cluster(self):
        doc = federated_snapshot()
        assert doc["rollup"]["n_nodes"] == 1
        assert "local" in doc["nodes"]

    def test_rollup_merge_and_deltas(self):
        def mk(p99, samples, flows):
            return lambda: {
                "slo": {"enabled": True, "objectives": [
                    {"p99_s": p99, "samples": samples, "breached": False},
                ]},
                "flowprof": {"enabled": True, "flows": flows},
            }

        doc = federated_snapshot({
            "fast": mk(0.010, 100, 10),
            "slow": mk(0.100, 100, 30),
        })
        r = doc["rollup"]
        assert r["node_p99_min_s"] == pytest.approx(0.010)
        assert r["node_p99_max_s"] == pytest.approx(0.100)
        # weighted nearest-rank over the windows lands on the slow node
        assert r["cluster_p99_s"] == pytest.approx(0.100)
        assert r["deltas"]["slow"]["p99_delta_s"] > 0
        assert r["deltas"]["fast"]["flows_delta"] == pytest.approx(-10.0)
        assert r["unhealthy_nodes"] == []

    def test_breached_objective_marks_node_unhealthy(self):
        doc = federated_snapshot({
            "sick": lambda: {
                "slo": {"enabled": True, "objectives": [
                    {"p99_s": 9.0, "samples": 5, "breached": True},
                ]},
            },
        })
        assert doc["rollup"]["unhealthy_nodes"] == ["sick"]

    def test_federated_prometheus_hostile_node_names(self):
        """node= label values with quotes, backslashes and newlines must
        not corrupt the scrape body."""
        hostile = 'evil"node\\with\nnewline'
        doc = federated_snapshot({
            hostile: lambda: {"slo": {"enabled": False}},
        })
        text = render_federated_prometheus(doc)
        samples = parse_prometheus(text)  # raises on any malformed line
        assert float(samples["cordatpu_cluster_nodes"]) == 1.0
        escaped = escape_label_value(hostile)
        assert "\n" not in escaped
        assert f'node="{escaped}"' in text


# ------------------------------------------------------- label escaping

class TestLabelEscaping:
    def test_escape_ordering_backslash_first(self):
        assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
        assert escape_label_value("plain") == "plain"

    def test_cluster_snapshot_rpc_surface(self):
        """CordaRPCOps.cluster_snapshot() without a registered handle is
        the single-node document, and the polled binding wraps it."""
        from corda_tpu.rpc.bindings import cluster_snapshot_value

        class FakeProxy:
            def cluster_snapshot(self):
                return federated_snapshot()

        val = cluster_snapshot_value(FakeProxy())
        doc = val.refresh()
        assert doc["rollup"]["n_nodes"] == 1


# -------------------------------------------- flight-dump forward-compat

class TestFlightDumpForwardCompat:
    def test_unknown_kind_round_trips_untouched(self, tmp_path):
        """A record written by a NEWER dumper must survive an old
        reader: it lands under ``extra`` verbatim instead of being
        dropped."""
        from corda_tpu.observability.slo import flight_dump, read_flight_dump

        path = flight_dump(str(tmp_path / "flight.jsonl"), reason="fc")
        alien = {"kind": "hologram", "payload": {"x": [1, 2, 3]}}
        with open(path, "a") as f:
            f.write(json.dumps(alien) + "\n")
        out = read_flight_dump(path)
        assert out["extra"] == [alien]
        assert out["header"]["reason"] == "fc"

    def test_net_kind_round_trips(self, tmp_path):
        from corda_tpu.observability.slo import flight_dump, read_flight_dump

        configure_netstats(enabled=True, reset=True)
        try:
            netstats().on_send("a", "b", "m1")
            netstats().on_deliver("a", "b", "m1")
            path = flight_dump(str(tmp_path / "f.jsonl"), reason="net")
            out = read_flight_dump(path)
            assert out["net"]["enabled"] is True
            assert "a->b" in out["net"]["edges"]
        finally:
            configure_netstats(enabled=False, reset=True)

    def test_net_kind_disabled_marker(self, tmp_path):
        from corda_tpu.observability.slo import flight_dump, read_flight_dump

        configure_netstats(enabled=False)
        path = flight_dump(str(tmp_path / "f.jsonl"), reason="off")
        out = read_flight_dump(path)
        assert out["net"] == {"enabled": False}


# ------------------------------------------------- off-by-default pins

class TestOffByDefaultPins:
    def test_sections_disabled_markers(self):
        configure_netstats(enabled=False)
        configure_cluster(enabled=False)
        assert netstats_section() == {"enabled": False}
        assert cluster_section() == {"enabled": False}

    def test_zero_names_when_off_fresh_subprocess(self):
        """netstats + cluster OFF (the default) through a REAL mocknet
        flow: bare disabled markers in the snapshot, NO net./cluster.
        registry names, and the hot-path checks hand back None — pinned
        in a fresh subprocess so no other test's configure_* latch can
        mask a regression."""
        code = """
import json, os
os.environ.pop("CORDA_TPU_NETSTATS", None)
os.environ.pop("CORDA_TPU_CLUSTER", None)
from corda_tpu.finance import CashIssueFlow
from corda_tpu.testing import MockNetworkNodes
from corda_tpu.node.monitoring import monitoring_snapshot, node_metrics
from corda_tpu.messaging.netstats import active_netstats
from corda_tpu.observability.cluster import active_cluster
with MockNetworkNodes() as net:
    alice = net.create_node("OffAlice")
    notary = net.create_notary_node("OffNotary")
    alice.run_flow(CashIssueFlow(100, "GBP", b"\\x01", notary.party))
snap = monitoring_snapshot()
assert snap["net"] == {"enabled": False}, snap["net"]
assert snap["cluster"] == {"enabled": False}, snap["cluster"]
names = list(node_metrics().snapshot())
assert not any(
    n.startswith(("net.", "cluster.")) for n in names
), names
assert active_netstats() is None
assert active_cluster() is None
print(json.dumps({"ok": True}))
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout.strip().splitlines()[-1])["ok"]
