"""Mesh fan-out tier tests — the production multi-device path
(SURVEY §2.9 P3: the reference's N-stateless-verifiers-on-one-queue,
Verifier.kt:66-84, re-shaped as batch sharding over a device mesh) on the
8-virtual-device CPU mesh from conftest.py.

Covers what the dryrun alone did not (r2 VERDICT weak #5): output shapes,
invalid-lane rejection on arbitrary shards, the spent-set all-gather
contents, and the SERVICE route — dispatch_signature_rows /
BatchedVerifierService actually reaching shard_map.
"""

import hashlib

import numpy as np
import pytest

import jax

from corda_tpu.parallel import (
    MeshVerifier,
    enable_service_mesh,
    make_mesh,
    service_mesh_active,
)


def _sigs(n, tag=b"mesh"):
    # signatures come from the repo's own host signer (OpenSSL when
    # installed, the portable engine otherwise) — the kernels under test
    # only care that the (pk, sig, msg) triples are valid RFC 8032
    from corda_tpu.crypto import EDDSA_ED25519_SHA512, derive_keypair_from_entropy
    from corda_tpu.crypto import sign as host_sign

    pks, sigs, msgs = [], [], []
    kp = derive_keypair_from_entropy(
        EDDSA_ED25519_SHA512, hashlib.sha256(tag).digest()
    )
    for i in range(n):
        m = b"CTSG" + hashlib.sha256(tag + i.to_bytes(4, "little")).digest() + bytes(8)
        pks.append(kp.public.encoded)
        sigs.append(host_sign(kp.private, m))
        msgs.append(m)
    return pks, sigs, msgs


@pytest.fixture(scope="module")
def mesh_verifier():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return MeshVerifier(make_mesh(8))


class TestMeshVerifier:
    def test_shapes_and_all_valid(self, mesh_verifier):
        pks, sigs, msgs = _sigs(24)
        spent = np.arange(24 * 8, dtype=np.int32).reshape(24, 8)
        mask, spent_all, total = mesh_verifier.dispatch_rows(
            pks, sigs, msgs, spent_hashes=spent
        )
        b = mask.shape[0]
        assert b % 8 == 0 and b >= 64  # bucket divisible over the mesh
        got = np.asarray(mask)
        assert got[:24].all() and not got[24:].any()  # pad lanes reject
        assert np.asarray(spent_all).shape == (b, 8)
        assert int(total) == 24

    def test_mask_only_path_skips_collectives(self, mesh_verifier):
        """Without spent hashes the verdict-only step runs (no all-gather
        per batch — the verifier-service fast path)."""
        pks, sigs, msgs = _sigs(16)
        mask, spent_all, total = mesh_verifier.dispatch_rows(pks, sigs, msgs)
        assert spent_all is None and total is None
        assert np.asarray(mask)[:16].all()

    def test_invalid_lanes_reject_on_any_shard(self, mesh_verifier):
        """Tampered lanes placed on different shards (index 1 → shard 0,
        index 60 → shard 7 at bucket 64) must each fail exactly."""
        pks, sigs, msgs = _sigs(64)
        sigs[1] = bytes([sigs[1][0] ^ 1]) + sigs[1][1:]
        msgs[60] = b"wrong message"
        pks[33] = bytes(32)  # not a curve point
        spent = np.zeros((64, 8), np.int32)
        mask, _spent, total = mesh_verifier.dispatch_rows(
            pks, sigs, msgs, spent_hashes=spent
        )
        got = np.asarray(mask)[:64]
        expect = np.ones(64, bool)
        expect[[1, 33, 60]] = False
        assert (got == expect).all()
        assert int(total) == 61

    def test_spent_hashes_all_gathered(self, mesh_verifier):
        """Every shard returns the COMPLETE consumed-set delta — the
        notary-commit collective (BASELINE north star's 'all-gather of
        spent-state hashes')."""
        pks, sigs, msgs = _sigs(16)
        spent = np.arange(16 * 8, dtype=np.int32).reshape(16, 8)
        mask, spent_all, _ = mesh_verifier.dispatch_rows(
            pks, sigs, msgs, spent_hashes=spent
        )
        got = np.asarray(spent_all)
        assert got.shape == (mask.shape[0], 8)
        assert (got[:16] == spent).all()
        assert not got[16:].any()

    def test_min_bucket_pins_compiled_shape(self, mesh_verifier):
        pks, sigs, msgs = _sigs(5)
        mask, _s, _t = mesh_verifier.dispatch_rows(
            pks, sigs, msgs, min_bucket=128
        )
        assert mask.shape[0] == 128


def _ecdsa_rows(n, scheme_id, tag=b"mesh-ecdsa"):
    from corda_tpu.crypto.schemes import (
        _HAVE_OPENSSL,
        derive_keypair_from_entropy,
        sign,
    )

    if not _HAVE_OPENSSL:
        pytest.skip("ECDSA signing needs the 'cryptography' package")
    pks, sigs, msgs = [], [], []
    for i in range(n):
        ent = hashlib.sha256(tag + i.to_bytes(4, "little")).digest()
        kp = derive_keypair_from_entropy(scheme_id, ent)
        m = b"mesh ecdsa lane %d" % i
        pks.append(bytes(kp.public.encoded))
        sigs.append(sign(kp.private, m))
        msgs.append(m)
    return pks, sigs, msgs


class TestMeshMixedScheme:
    """The mixed-scheme fan-out (r3 VERDICT weak #5 / task 4): ECDSA
    buckets shard over the mesh like ed25519; SPHINCS fans out as
    per-device chunk streams. Reference: the worker fan-out serves ALL
    verification work, Verifier.kt:66-84."""

    def test_ecdsa_k1_over_mesh(self, mesh_verifier):
        from corda_tpu.crypto.schemes import ECDSA_SECP256K1_SHA256

        pks, sigs, msgs = _ecdsa_rows(24, ECDSA_SECP256K1_SHA256)
        # adversarial lanes on distinct shards at bucket 64
        sigs[2] = sigs[2][:10] + bytes([sigs[2][10] ^ 1]) + sigs[2][11:]
        msgs[17] = b"wrong message"
        pks[9] = bytes(33)  # not a curve point
        mask = mesh_verifier.dispatch_ecdsa_rows(
            "secp256k1", pks, sigs, msgs
        )
        assert mask.shape[0] % 8 == 0
        got = np.asarray(mask)[:24]
        expect = np.ones(24, bool)
        expect[[2, 9, 17]] = False
        assert (got == expect).all()

    def test_ecdsa_r1_over_mesh_min_bucket(self, mesh_verifier):
        from corda_tpu.crypto.schemes import ECDSA_SECP256R1_SHA256

        pks, sigs, msgs = _ecdsa_rows(5, ECDSA_SECP256R1_SHA256, b"r1")
        mask = mesh_verifier.dispatch_ecdsa_rows(
            "secp256r1", pks, sigs, msgs, min_bucket=128
        )
        assert mask.shape[0] == 128
        assert np.asarray(mask)[:5].all()

    def test_sphincs_chunk_fanout(self, mesh_verifier):
        from corda_tpu.crypto import sphincs

        pks, sigs, msgs = [], [], []
        for i in range(3):
            pk, sk = sphincs.generate(bytes([40 + i]) * 32)
            m = b"mesh sphincs lane %d" % i
            pks.append(pk)
            sigs.append(sphincs.sign(sk, m))
            msgs.append(m)
        # duplicate lanes to span several chunks; tamper two of them
        pks, sigs, msgs = pks * 3, sigs * 3, msgs * 3
        sigs[1] = sigs[1][:40] + bytes([sigs[1][40] ^ 1]) + sigs[1][41:]
        msgs[7] = b"wrong"
        mask = mesh_verifier.dispatch_sphincs_rows(pks, sigs, msgs)
        got = np.asarray(mask)
        expect = np.ones(9, bool)
        expect[[1, 7]] = False
        assert got.shape == (9,)
        assert (got == expect).all()

    def test_service_routes_ecdsa_through_mesh(self):
        """dispatch_signature_rows' ECDSA bucket reaches the mesh when
        active — the service seam for the mixed-scheme fan-out."""
        from corda_tpu.crypto.keys import PublicKey
        from corda_tpu.crypto.schemes import (
            ECDSA_SECP256K1_SHA256,
            EDDSA_ED25519_SHA512,
        )
        from corda_tpu.verifier import dispatch_signature_rows

        epks, esigs, emsgs = _sigs(6, b"mixed-ed")
        kpks, ksigs, kmsgs = _ecdsa_rows(6, ECDSA_SECP256K1_SHA256, b"mx")
        ksigs[3] = ksigs[3][:5] + bytes([ksigs[3][5] ^ 1]) + ksigs[3][6:]
        esigs[2] = bytes([esigs[2][0] ^ 1]) + esigs[2][1:]
        rows = [
            (PublicKey(EDDSA_ED25519_SHA512, pk), sig, msg)
            for pk, sig, msg in zip(epks, esigs, emsgs)
        ] + [
            (PublicKey(ECDSA_SECP256K1_SHA256, pk), sig, msg)
            for pk, sig, msg in zip(kpks, ksigs, kmsgs)
        ]
        enable_service_mesh(True)
        try:
            got = dispatch_signature_rows(rows).collect()
        finally:
            enable_service_mesh(False)
        expect = np.ones(12, bool)
        expect[[2, 9]] = False  # ed lane 2, ecdsa lane 3 (row 6+3)
        assert (got == expect).all()


class TestServiceMeshRouting:
    def test_dispatch_rows_routes_through_mesh(self):
        """The service seam: with the mesh enabled,
        dispatch_signature_rows' ed25519 bucket goes through shard_map and
        still returns a correct deferred mask (r2 VERDICT missing #2 —
        mesh code reachable from a service)."""
        from corda_tpu.crypto.keys import PublicKey
        from corda_tpu.crypto.schemes import EDDSA_ED25519_SHA512
        from corda_tpu.verifier import dispatch_signature_rows

        pks, sigs, msgs = _sigs(12)
        sigs[4] = bytes([sigs[4][0] ^ 1]) + sigs[4][1:]
        rows = [
            (PublicKey(EDDSA_ED25519_SHA512, pk), sig, msg)
            for pk, sig, msg in zip(pks, sigs, msgs)
        ]
        enable_service_mesh(True)
        try:
            assert service_mesh_active()
            got = dispatch_signature_rows(rows).collect()
        finally:
            enable_service_mesh(False)
        expect = np.ones(12, bool)
        expect[4] = False
        assert (got == expect).all()

    def test_batched_verifier_service_over_mesh(self):
        """End-to-end: BatchedVerifierService verifying real transactions
        with the mesh fan-out under it."""
        from corda_tpu.testing import GeneratedLedger
        from corda_tpu.verifier import BatchedVerifierService

        gen = GeneratedLedger(seed=21)
        txs = list(gen.generate(6, with_notary_sig=True).values())

        def resolve(ref):
            return gen.transactions[ref.txhash].tx.outputs[ref.index]

        enable_service_mesh(True)
        try:
            svc = BatchedVerifierService(max_batch=8, window_s=0.002)
            notary_keys = {
                stx.tx.notary.owning_key for stx in txs
            }
            futs = [
                svc.verify_signed(stx, resolve, allowed_missing=notary_keys)
                for stx in txs
            ]
            for f in futs:
                assert f.result(timeout=120) is None
            svc.shutdown()
        finally:
            enable_service_mesh(False)

    def test_single_chip_degrade_is_transparent(self):
        """Mesh off → the same rows verify via the plain dispatch (the
        transparent degrade VERDICT asked for)."""
        from corda_tpu.crypto.keys import PublicKey
        from corda_tpu.crypto.schemes import EDDSA_ED25519_SHA512
        from corda_tpu.verifier import dispatch_signature_rows

        assert not service_mesh_active()  # CPU default: off
        pks, sigs, msgs = _sigs(4)
        rows = [
            (PublicKey(EDDSA_ED25519_SHA512, pk), sig, msg)
            for pk, sig, msg in zip(pks, sigs, msgs)
        ]
        assert dispatch_signature_rows(rows).collect().all()
