"""Tests for the batched verification engine + wavefront DAG scheduler
(the TPU-native replacements for the reference's verification tier —
InMemoryTransactionVerifierService / Verifier.kt / ResolveTransactionsFlow).

Device usage is confined to the explicitly-marked tests; everything else
exercises the same code paths with the host crypto oracle so failures
localize (kernel correctness has its own differential suite in
test_ops_ed25519.py).
"""

import dataclasses

import pytest

from corda_tpu.crypto import generate_keypair, sign_tx_id
from corda_tpu.ledger import (
    CordaX500Name,
    Party,
    SignedTransaction,
    StateRef,
    TransactionBuilder,
)
from corda_tpu.ledger.signed import SignaturesMissingException
from corda_tpu.parallel import (
    DoubleSpendInDagError,
    UnresolvedStateError,
    topological_levels,
    verify_transaction_dag,
)
from corda_tpu.serialization import register_custom
from corda_tpu.verifier import (
    BatchedVerifierService,
    check_transactions,
    verify_signature_rows,
)
from corda_tpu.verifier.batch import InvalidSignatureError
from corda_tpu.ledger.states import register_contract


@dataclasses.dataclass(frozen=True)
class CoinState:
    value: int
    owner_key: object = None

    @property
    def participants(self):
        return []


@dataclasses.dataclass(frozen=True)
class CoinCommand:
    op: str


register_custom(
    CoinState, "test.CoinState",
    to_fields=lambda s: {"value": s.value, "owner_key": s.owner_key or 0},
    from_fields=lambda d: CoinState(d["value"], d["owner_key"] or None),
)
register_custom(
    CoinCommand, "test.CoinCommand",
    to_fields=lambda c: {"op": c.op},
    from_fields=lambda d: CoinCommand(d["op"]),
)


@register_contract("test.CoinContract")
class CoinContract:
    def verify(self, tx):
        ins = sum(s.value for s in tx.inputs_of_type(CoinState))
        outs = sum(s.value for s in tx.outputs_of_type(CoinState))
        cmds = tx.commands_of_type(CoinCommand)
        if not cmds:
            raise ValueError("no CoinCommand")
        op = cmds[0].value.op
        if op == "issue" and tx.inputs:
            raise ValueError("issue must not consume")
        if op == "move" and ins != outs:
            raise ValueError(f"value not conserved: {ins} -> {outs}")


@pytest.fixture(scope="module")
def notary():
    kp = generate_keypair()
    return Party(CordaX500Name("Notary", "Zurich", "CH"), kp.public), kp


@pytest.fixture(scope="module")
def alice():
    kp = generate_keypair()
    return Party(CordaX500Name("Alice", "London", "GB"), kp.public), kp


def issue_tx(notary, alice, value=100) -> SignedTransaction:
    b = TransactionBuilder(notary=notary[0])
    b.add_output_state(CoinState(value), "test.CoinContract")
    b.add_command(CoinCommand("issue"), alice[1].public)
    return b.sign_initial_transaction(alice[1])


def move_tx(notary, alice, parent: SignedTransaction, idx=0, split=None):
    """Spend parent's output ``idx`` into one or two outputs."""
    b = TransactionBuilder(notary=notary[0])
    parent_state = parent.tx.outputs[idx]
    b._inputs.append(StateRef(parent.id, idx))
    b._ensure_attachment(parent_state.contract)
    value = parent_state.data.value
    if split:
        b.add_output_state(CoinState(split), "test.CoinContract")
        b.add_output_state(CoinState(value - split), "test.CoinContract")
    else:
        b.add_output_state(CoinState(value), "test.CoinContract")
    b.add_command(CoinCommand("move"), alice[1].public)
    wtx = b.to_wire_transaction()
    sigs = [
        sign_tx_id(alice[1].private, alice[1].public, wtx.id),
        sign_tx_id(notary[1].private, notary[1].public, wtx.id),
    ]
    return SignedTransaction.create(wtx, sigs)


# -------------------------------------------------------------- batch check

class TestBatchCheck:
    def test_rows_mixed_validity(self, notary, alice):
        stx = issue_tx(notary, alice)
        rows = stx.signature_triples()
        good = [(k, s, m) for k, s, m in rows]
        bad = [(k, s[:-1] + bytes([s[-1] ^ 1]), m) for k, s, m in rows]
        mask = verify_signature_rows(good + bad, use_device=False)
        assert mask.tolist() == [True] * len(good) + [False] * len(bad)

    def test_rows_mixed_schemes_device_dispatch(self):
        """BASELINE config #3 shape: one flattened row set spanning
        ed25519 + secp256k1 + secp256r1 (device buckets) + SPHINCS (host
        bucket), with invalid lanes in each bucket. Exercises the real
        scheme-bucketed device dispatch on CPU-backed kernels."""
        from corda_tpu.crypto import schemes as cs

        if not cs._HAVE_OPENSSL:
            pytest.skip("ECDSA signing needs the 'cryptography' package")
        rows, want = [], []
        for sid in (
            cs.EDDSA_ED25519_SHA512,
            cs.ECDSA_SECP256K1_SHA256,
            cs.ECDSA_SECP256R1_SHA256,
            cs.SPHINCS256_SHA256,
        ):
            for j in range(3):
                kp = cs.generate_keypair(sid)
                msg = b"row-%d-%d" % (sid, j)
                sig = cs.sign(kp.private, msg)
                if j == 1:  # tamper the middle lane of every bucket
                    msg = msg + b"!"
                rows.append((kp.public, sig, msg))
                want.append(j != 1)
        mask = verify_signature_rows(rows, use_device=True)
        assert mask.tolist() == want

    def test_check_transactions_ok(self, notary, alice):
        stxs = [issue_tx(notary, alice, v) for v in (1, 2, 3)]
        report = check_transactions(stxs, use_device=False)
        assert report.ok and report.n_sigs == 3

    def test_check_transactions_bad_sig(self, notary, alice):
        good = issue_tx(notary, alice, 1)
        victim = issue_tx(notary, alice, 2)
        sig = victim.sigs[0]
        forged = dataclasses.replace(
            victim,
            sigs=(dataclasses.replace(
                sig, signature=sig.signature[:-1] + bytes([sig.signature[-1] ^ 1])
            ),),
        )
        report = check_transactions([good, forged], use_device=False)
        assert report.results[0] is None
        assert isinstance(report.results[1], InvalidSignatureError)
        with pytest.raises(InvalidSignatureError):
            report.raise_first()

    def test_check_transactions_missing_signer(self, notary, alice):
        stx = move_tx(notary, alice, issue_tx(notary, alice))
        # strip the notary signature: required (tx has inputs) but absent
        stripped = dataclasses.replace(stx, sigs=stx.sigs[:1])
        report = check_transactions([stripped], use_device=False)
        assert isinstance(report.results[0], SignaturesMissingException)
        # ...and allowed_missing covering the notary key makes it pass
        report = check_transactions(
            [stripped], [{notary[0].owning_key}], use_device=False
        )
        assert report.ok

    @pytest.mark.device
    def test_check_transactions_on_device(self, notary, alice):
        stxs = [issue_tx(notary, alice, v) for v in (5, 6)]
        report = check_transactions(stxs, use_device=True)
        assert report.ok and report.n_device == 2


# ----------------------------------------------------------- batched service

class TestBatchedService:
    def test_batches_concurrent_requests(self, notary, alice):
        # the self-contained windowed flusher (use_scheduler=False): the
        # batches<=3 assertion is a property of the window, not of the
        # serving scheduler's continuous batching (tests/test_serving.py)
        svc = BatchedVerifierService(
            window_s=0.05, use_device=False, workers=4, use_scheduler=False
        )
        try:
            chain = [issue_tx(notary, alice, 10)]
            for _ in range(5):
                chain.append(move_tx(notary, alice, chain[-1]))
            states = {
                StateRef(stx.id, i): ts
                for stx in chain
                for i, ts in enumerate(stx.tx.outputs)
            }
            futs = [
                svc.verify_signed(stx, states.get, {notary[0].owning_key})
                for stx in chain
            ]
            for f in futs:
                assert f.result(timeout=30) is None
            assert svc.stats["txs"] == 6
            # the window should have coalesced these into few batches
            assert svc.stats["batches"] <= 3
        finally:
            svc.shutdown()

    def test_failure_propagates(self, notary, alice):
        svc = BatchedVerifierService(window_s=0.01, use_device=False)
        try:
            stx = issue_tx(notary, alice)
            sig = stx.sigs[0]
            forged = dataclasses.replace(
                stx,
                sigs=(dataclasses.replace(
                    sig,
                    signature=sig.signature[:-1] + bytes([sig.signature[-1] ^ 1]),
                ),),
            )
            fut = svc.verify_signed(forged)
            with pytest.raises(InvalidSignatureError):
                fut.result(timeout=30)
        finally:
            svc.shutdown()


# -------------------------------------------------------------- wavefront

class TestWavefront:
    def test_topological_levels(self):
        deps = {1: set(), 2: {1}, 3: {1}, 4: {2, 3}, 5: {9}}  # 9 external
        levels = topological_levels(deps)
        assert levels[0] == sorted(levels[0]) or set(levels[0]) == {1, 5}
        assert set(levels[0]) == {1, 5}
        assert set(levels[1]) == {2, 3}
        assert levels[2] == [4]

    def test_cycle_detected(self):
        with pytest.raises(Exception, match="cycle"):
            topological_levels({1: {2}, 2: {1}})

    def _chain(self, notary, alice, depth):
        chain = [issue_tx(notary, alice, 64)]
        for _ in range(depth):
            chain.append(move_tx(notary, alice, chain[-1]))
        return chain

    def test_chain_verifies_in_levels(self, notary, alice):
        chain = self._chain(notary, alice, 6)
        res = verify_transaction_dag(
            {s.id: s for s in chain}, use_device=False
        )
        assert len(res.levels) == 7  # a pure chain gives one tx per level
        assert res.order[0] == chain[0].id
        assert res.n_sigs == 1 + 6 * 2

    def test_diamond_dag_parallel_level(self, notary, alice):
        root = issue_tx(notary, alice, 100)
        split = move_tx(notary, alice, root, split=40)
        a = move_tx(notary, alice, split, idx=0)
        b = move_tx(notary, alice, split, idx=1)
        res = verify_transaction_dag(
            {s.id: s for s in (root, split, a, b)}, use_device=False
        )
        assert set(res.levels[2]) == {a.id, b.id}  # the wavefront batch

    def test_double_spend_rejected(self, notary, alice):
        root = issue_tx(notary, alice, 100)
        s1 = move_tx(notary, alice, root)
        s2 = move_tx(notary, alice, root, split=1)  # also spends root:0
        with pytest.raises(DoubleSpendInDagError):
            verify_transaction_dag(
                {s.id: s for s in (root, s1, s2)}, use_device=False
            )

    def test_unresolved_input_rejected(self, notary, alice):
        orphan = move_tx(notary, alice, issue_tx(notary, alice))
        with pytest.raises(UnresolvedStateError):
            verify_transaction_dag({orphan.id: orphan}, use_device=False)

    def test_external_resolution(self, notary, alice):
        root = issue_tx(notary, alice, 7)
        child = move_tx(notary, alice, root)
        states = {
            StateRef(root.id, i): ts for i, ts in enumerate(root.tx.outputs)
        }
        res = verify_transaction_dag(
            {child.id: child}, resolve_external=states.get, use_device=False
        )
        assert res.order == [child.id]

    def test_contract_rejection_surfaces(self, notary, alice):
        root = issue_tx(notary, alice, 50)
        bad = move_tx(notary, alice, root)
        # tamper: rebuild the move with non-conserving outputs
        b = TransactionBuilder(notary=notary[0])
        b._inputs.append(StateRef(root.id, 0))
        b._ensure_attachment("test.CoinContract")
        b.add_output_state(CoinState(49), "test.CoinContract")
        b.add_command(CoinCommand("move"), alice[1].public)
        wtx = b.to_wire_transaction()
        bad = SignedTransaction.create(wtx, [
            sign_tx_id(alice[1].private, alice[1].public, wtx.id),
            sign_tx_id(notary[1].private, notary[1].public, wtx.id),
        ])
        with pytest.raises(Exception, match="not conserved"):
            verify_transaction_dag(
                {s.id: s for s in (root, bad)}, use_device=False
            )


# ------------------------------------------------- SPHINCS routing override

class TestSphincsRoutingOverride:
    def test_forced_device_outranks_backend_gate(self, monkeypatch):
        """CORDA_TPU_SPHINCS=device must route scheme 5 to the device
        tier on ANY accelerator backend — without even consulting the
        backend gate (the override exists precisely to pin routing on
        non-TPU backends)."""
        import jax

        from corda_tpu.crypto import SPHINCS256_SHA256
        from corda_tpu.verifier.batch import _effective_device_schemes

        monkeypatch.setenv("CORDA_TPU_SPHINCS", "device")

        def boom():
            raise AssertionError("backend gate consulted under override")

        monkeypatch.setattr(jax, "default_backend", boom)
        assert SPHINCS256_SHA256 in _effective_device_schemes(True)

    def test_forced_host_and_backend_default(self, monkeypatch):
        import jax

        from corda_tpu.crypto import SPHINCS256_SHA256
        from corda_tpu.verifier.batch import _effective_device_schemes

        monkeypatch.setenv("CORDA_TPU_SPHINCS", "host")
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert SPHINCS256_SHA256 not in _effective_device_schemes(True)
        # no override: route by backend — TPU on, anything else off
        monkeypatch.delenv("CORDA_TPU_SPHINCS")
        assert SPHINCS256_SHA256 in _effective_device_schemes(True)
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert SPHINCS256_SHA256 not in _effective_device_schemes(True)
        # host-only dispatch never routes any scheme to device
        assert _effective_device_schemes(False) == set()
