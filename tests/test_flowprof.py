"""Flow critical-path accounting tests — the flowprof phase ledger
(frames / cross-thread adds / park hints, conservation to the flow
wall), the timed SMM lock, the wall-clock stack sampler's overhead
budget, the off-by-default zero-overhead pin (fresh subprocess), and
the flight-dump round-trip of both new sections."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from corda_tpu.observability.flowprof import (
    PHASES,
    FlowProfiler,
    configure_flowprof,
    flowprof,
    flowprof_frame,
    flowprof_section,
)
from corda_tpu.observability.sampler import (
    StackSampler,
    configure_sampler,
    _role_of,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def fp():
    clock = FakeClock()
    prof = FlowProfiler(clock=clock)
    prof.enable()
    prof.clock = clock  # test handle
    return prof


# ------------------------------------------------------------ the ledger

class TestPhaseLedger:
    def test_phase_set_is_closed_and_residual_last(self):
        assert len(PHASES) == 10
        assert len(set(PHASES)) == 10
        assert PHASES[-1] == "engine_other"

    def test_frame_exclusive_time_nesting(self, fp):
        """A nested frame's wall is subtracted from its parent: a
        checkpoint that spends most of its time inside wal_fsync_wait
        books only its exclusive share."""
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("checkpoint"):
                fp.clock.advance(1.0)
                with fp.frame("wal_fsync_wait"):
                    fp.clock.advance(3.0)
                fp.clock.advance(1.0)
        assert acct.phases["checkpoint"] == pytest.approx(2.0)
        assert acct.phases["wal_fsync_wait"] == pytest.approx(3.0)

    def test_same_phase_nesting_sums_once(self, fp):
        """Engine serialize wrapping a broker serialize (same phase,
        nested) must book the outer elapsed exactly once."""
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("serialize"):
                fp.clock.advance(0.5)
                with fp.frame("serialize"):
                    fp.clock.advance(2.0)
                fp.clock.advance(0.5)
        assert acct.phases["serialize"] == pytest.approx(3.0)

    def test_frames_are_noops_without_activation(self, fp):
        acct = fp.open("f1", "test.Flow")
        with fp.frame("serialize"):   # no activate() on this thread
            fp.clock.advance(1.0)
        assert acct.phases["serialize"] == 0.0

    def test_close_residual_conserves_wall(self, fp):
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("serialize"):
                fp.clock.advance(2.0)
        fp.add(acct, "queue_wait", 1.5)
        fp.clock.advance(6.5)
        out = fp.close("f1")
        assert out is not None and out["wall_s"] == pytest.approx(8.5)
        assert set(out["phases"]) == set(PHASES)
        assert out["phases"]["engine_other"] == pytest.approx(5.0)
        assert sum(out["phases"].values()) == pytest.approx(out["wall_s"])

    def test_overattribution_clamps_residual_to_zero(self, fp):
        """Cross adds can overshoot the wall (overlapping attributions
        are a bug the conservation tests exist to catch); the residual
        clamps at zero so the overshoot stays visible in the sum."""
        acct = fp.open("f1", "test.Flow")
        fp.add(acct, "device_execute", 99.0)
        fp.clock.advance(1.0)
        out = fp.close("f1")
        assert out["phases"]["engine_other"] == 0.0
        assert sum(out["phases"].values()) > out["wall_s"]

    def test_hint_park_attribution_subtracts_cross_adds(self, fp):
        """A hinted park books (park wall - cross adds inside the
        window) to the hinted phase: the notary response's transit is
        never double-booked under notary_rtt."""
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.hint("notary_rtt"):
                fp.note_park(acct)
                fp.clock.advance(5.0)
                fp.add(acct, "message_transit", 2.0)  # response transit
                fp.note_unpark(acct)
        assert acct.phases["notary_rtt"] == pytest.approx(3.0)
        assert acct.phases["message_transit"] == pytest.approx(2.0)
        assert acct.hint is None  # scope restored

    def test_unhinted_park_falls_into_residual(self, fp):
        """No hint → no park window: 'waiting on a counterparty we
        cannot see into' is honestly engine_other."""
        acct = fp.open("f1", "test.Flow")
        fp.note_park(acct)
        assert acct.park_t0 is None
        fp.clock.advance(4.0)
        fp.note_unpark(acct)
        out = fp.close("f1")
        assert out["phases"]["engine_other"] == pytest.approx(4.0)
        assert out["phases"]["notary_rtt"] == 0.0

    def test_add_after_close_is_dropped(self, fp):
        acct = fp.open("f1", "test.Flow")
        fp.close("f1")
        fp.add(acct, "queue_wait", 3.0)
        assert acct.phases["queue_wait"] == 0.0

    def test_transit_stamp_roundtrip(self, fp):
        acct = fp.open("f1", "test.Flow")
        fp.note_sent("m1")
        fp.clock.advance(0.25)
        fp.take_transit("m1", acct)
        fp.take_transit("m1", acct)        # stamp consumed: second no-op
        fp.take_transit("never-sent", acct)
        assert acct.phases["message_transit"] == pytest.approx(0.25)

    def test_live_cap_bounds_leaked_flows(self, fp):
        for i in range(fp.LIVE_CAP + 5):
            fp.open(f"f{i}", "test.Flow")
        assert fp.acct_of("f0") is None           # oldest evicted
        assert fp.acct_of(f"f{fp.LIVE_CAP + 4}") is not None

    def test_snapshot_classes_and_shares(self, fp):
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("serialize"):
                fp.clock.advance(1.0)
        fp.clock.advance(1.0)
        fp.close("f1")
        snap = fp.snapshot()
        assert snap["enabled"] and snap["flows"] == 1
        agg = snap["classes"]["test.Flow"]
        assert agg["flows"] == 1
        assert agg["wall_s"] == pytest.approx(2.0)
        assert set(agg["phases"]) == set(PHASES)
        assert sum(agg["shares"].values()) == pytest.approx(1.0)
        assert agg["shares"]["serialize"] == pytest.approx(0.5)


# ------------------------------------------------------------ timed lock

class TestTimedRLock:
    def test_contended_acquire_books_lock_wait(self):
        prof = FlowProfiler()
        prof.enable()
        lock = prof.timed_rlock()
        acct = prof.open("f1", "test.Flow")
        lock.acquire()

        def holder_release():
            time.sleep(0.15)
            lock.release()

        # hold from main, release from a timer-ish thread while a second
        # thread (with the account active) blocks on acquire
        waited = {}

        def waiter():
            with prof.activate(acct):
                t0 = time.monotonic()
                lock2_ok = False
                # a fresh thread cannot release main's RLock; it blocks
                # until holder_release fires
                lock.acquire()
                lock2_ok = True
                lock.release()
                waited["wall"] = time.monotonic() - t0
                waited["ok"] = lock2_ok

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.15)
        lock.release()
        t.join(timeout=5)
        assert waited["ok"]
        assert acct.phases["lock_wait"] >= 0.1
        assert acct.phases["lock_wait"] <= waited["wall"] + 0.05

    def test_uncontended_acquire_books_nothing(self):
        prof = FlowProfiler()
        prof.enable()
        lock = prof.timed_rlock()
        acct = prof.open("f1", "test.Flow")
        with prof.activate(acct):
            with lock:
                with lock:   # reentrant
                    pass
        assert acct.phases["lock_wait"] == 0.0

    def test_condition_wait_notify_roundtrip(self):
        """The SMM wraps the timed lock in a Condition — wait/notify
        must work through the _release_save/_acquire_restore hooks, and
        the woken waiter's monitor reacquire must NOT book lock_wait
        (scheduling, not contention)."""
        prof = FlowProfiler()
        prof.enable()
        cv = threading.Condition(prof.timed_rlock())
        acct = prof.open("f1", "test.Flow")
        state = {"go": False}

        def waiter():
            with prof.activate(acct):
                with cv:
                    cv.wait_for(lambda: state["go"], timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        with cv:
            state["go"] = True
            cv.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        # the only acquire the waiter timed was its (uncontended) entry
        assert acct.phases["lock_wait"] < 0.05


# ---------------------------------------------- traced flow conservation

class TestTracedPaymentFlow:
    def test_payment_waterfall_conserves_wall(self):
        """The ISSUE's acceptance path: a profiled mocknet payment's
        phases are drawn from the closed set and sum to the flow wall
        within 5% (the engine's residual makes conservation structural;
        the tolerance absorbs cross-thread adds racing close)."""
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
        from corda_tpu.flows.api import class_path
        from corda_tpu.testing import MockNetworkNodes

        configure_flowprof(enabled=True, reset=True)
        try:
            with MockNetworkNodes() as net:
                alice = net.create_node("ProfAlice")
                bob = net.create_node("ProfBob")
                notary = net.create_notary_node("ProfNotary")
                alice.run_flow(
                    CashIssueFlow(100, "GBP", b"\x01", notary.party)
                )
                alice.run_flow(CashPaymentFlow(40, "GBP", bob.party))
            snap = flowprof().snapshot()
            pay_cls = class_path(CashPaymentFlow)
            assert pay_cls in snap["classes"], list(snap["classes"])
            for rec in snap["recent"]:
                assert set(rec["phases"]) == set(PHASES)
                assert all(v >= 0 for v in rec["phases"].values())
                total = sum(rec["phases"].values())
                assert abs(total - rec["wall_s"]) <= 0.05 * rec["wall_s"], (
                    rec["flow_class"], total, rec["wall_s"])
            pay = next(
                r for r in snap["recent"] if r["flow_class"] == pay_cls
            )
            # the phases the payment's critical path must traverse
            assert pay["phases"]["checkpoint"] > 0
            assert pay["phases"]["serialize"] > 0
            assert pay["phases"]["notary_rtt"] > 0
            # the ledger fed the registry timers
            from corda_tpu.node.monitoring import (
                monitoring_snapshot, node_metrics,
            )
            names = list(node_metrics().snapshot())
            assert "flowprof.phase.notary_rtt" in names
            assert "flowprof.wall_s" in names
            msnap = monitoring_snapshot()
            assert msnap["flowprof"]["enabled"]
            assert msnap["flowprof"]["flows"] >= snap["flows"] - 1
        finally:
            configure_flowprof(enabled=False, reset=True)


# ----------------------------------------------------------- the sampler

class TestSampler:
    def test_role_mapping(self):
        assert _role_of("flow-worker-3") == "flow_worker"
        assert _role_of("serving-dispatch") == "dispatcher"
        assert _role_of("serving-collect-1") == "collector"
        assert _role_of("wal-writer") == "fsync"
        assert _role_of("MainThread") == "main"
        assert _role_of("weird-thread") == "other"

    def test_overhead_ratio_math_fake_clock(self):
        """overhead_ratio = busy / elapsed, against the injected clock —
        the <3% budget's measured side, pinned arithmetically."""
        clock = FakeClock()
        s = StackSampler(hz=100, clock=clock)
        s.reset()                      # started_at = clock()
        clock.advance(10.0)
        s._busy_s = 0.2                # what the loop would have booked
        assert s.overhead_ratio() == pytest.approx(0.02)
        s.reset()
        assert s.overhead_ratio() == 0.0

    def test_sample_once_folds_foreign_threads(self):
        s = StackSampler(hz=100)
        stop = threading.Event()

        def parked_worker():
            stop.wait(5)

        t = threading.Thread(
            target=parked_worker, name="flow-worker-9", daemon=True
        )
        t.start()
        try:
            time.sleep(0.05)
            recorded = s.sample_once()
            assert recorded >= 1       # at least the worker thread
            dump = s.dump()
            assert "flow_worker" in dump["roles"], list(dump["roles"])
            folded, count = dump["roles"]["flow_worker"][0]
            assert count >= 1
            # root-first flamegraph line through the worker body
            assert ";" in folded and "parked_worker" in folded
        finally:
            stop.set()
            t.join(timeout=5)

    def test_real_thread_overhead_under_budget(self):
        """A live 100 Hz sampler over busy threads stays under the 3%
        overhead budget (the loop self-throttles by sleeping the
        remainder of each period)."""
        stop = threading.Event()

        def busy():
            x = 0
            while not stop.is_set():
                x = (x + 1) % 1000003

        workers = [
            threading.Thread(target=busy, name=f"flow-worker-{i}",
                             daemon=True)
            for i in range(3)
        ]
        for w in workers:
            w.start()
        s = StackSampler(hz=100)
        s.start()
        try:
            time.sleep(0.8)
            ratio = s.overhead_ratio()
            dump = s.dump(top_n=10)
        finally:
            s.stop()
            stop.set()
            for w in workers:
                w.join(timeout=5)
        assert dump["samples"] >= 20, dump["samples"]
        assert ratio < 0.03, f"sampler overhead {ratio:.4f} >= 3% budget"
        assert "flow_worker" in dump["roles"]
        assert all(
            len(bucket) <= 10 for bucket in dump["roles"].values()
        )


# ------------------------------------------------- off-by-default pin

class TestOffByDefaultPins:
    def test_zero_overhead_when_off(self):
        """flowprof + sampler OFF (the default) through a REAL mocknet
        flow: no flowprof.*/sampler.* registry names, no sampler thread,
        bare disabled markers in the snapshot, and the hook helper hands
        back the shared no-op frame — pinned in a fresh subprocess so no
        other test's configure_* latch can mask a regression."""
        code = """
import json, os, threading
os.environ.pop("CORDA_TPU_FLOWPROF", None)
os.environ.pop("CORDA_TPU_SAMPLER", None)
from corda_tpu.finance import CashIssueFlow
from corda_tpu.testing import MockNetworkNodes
from corda_tpu.node.monitoring import monitoring_snapshot, node_metrics
from corda_tpu.observability.flowprof import flowprof_frame, flowprof
with MockNetworkNodes() as net:
    alice = net.create_node("OffAlice")
    notary = net.create_notary_node("OffNotary")
    alice.run_flow(CashIssueFlow(100, "GBP", b"\\x01", notary.party))
snap = monitoring_snapshot()
assert snap["flowprof"] == {"enabled": False}, snap["flowprof"]
assert snap["sampler"] == {"enabled": False}, snap["sampler"]
names = list(node_metrics().snapshot())
assert not any(
    n.startswith(("flowprof.", "sampler.")) for n in names
), names
assert not any(
    t.name == "stack-sampler" for t in threading.enumerate()
), [t.name for t in threading.enumerate()]
# hooks hand back one shared no-op object — zero allocation per call
assert flowprof_frame("serialize") is flowprof_frame("checkpoint")
# nothing was ledgered while off
assert flowprof().snapshot()["flows"] == 0
print(json.dumps({"ok": True}))
"""
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": REPO + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
            cwd=REPO,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout.strip().splitlines()[-1])["ok"]


# ------------------------------------------------- flight-dump round-trip

class TestFlightDumpRoundTrip:
    def test_sections_disabled_round_trip(self, tmp_path):
        from corda_tpu.observability.slo import flight_dump, read_flight_dump

        configure_flowprof(enabled=False)
        configure_sampler(enabled=False)
        path = flight_dump(str(tmp_path / "flight.jsonl"), reason="test")
        out = read_flight_dump(path)
        assert out["flowprof"] == {"enabled": False}
        assert out["sampler"] == {"enabled": False}

    def test_sections_enabled_round_trip(self, tmp_path):
        """With both knobs on, the dump carries the waterfall and the
        folded stacks, and read_flight_dump hands them back typed."""
        from corda_tpu.observability.slo import flight_dump, read_flight_dump

        configure_flowprof(enabled=True, reset=True)
        configure_sampler(enabled=True, hz=100, reset=True)
        try:
            prof = flowprof()
            prof.open("f1", "test.DumpFlow")
            time.sleep(0.05)
            prof.close("f1")
            deadline = time.monotonic() + 5
            while (configure_sampler().dump()["samples"] < 3
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            path = flight_dump(str(tmp_path / "flight.jsonl"),
                               reason="test")
            out = read_flight_dump(path)
            assert out["flowprof"]["enabled"]
            assert "test.DumpFlow" in out["flowprof"]["classes"]
            rec = out["flowprof"]["recent"][-1]
            assert set(rec["phases"]) == set(PHASES)
            assert out["sampler"]["enabled"]
            assert out["sampler"]["samples"] >= 3
            assert isinstance(out["sampler"]["roles"], dict)
            # the dump is JSON all the way down (no stray objects)
            json.dumps(out["sampler"])
            # monitoring_snapshot carries the same sections
            assert flowprof_section()["flows"] >= 1
        finally:
            configure_flowprof(enabled=False, reset=True)
            configure_sampler(enabled=False, reset=True)


# ------------------------------------------- cause-bucket conservation

class TestCauseLedger:
    """The concurrency observatory's cause split: every phase's
    aggregate wall divides into on_cpu / lock_wait / io_wait /
    gil_runnable / unattributed buckets that CONSERVE to the phase total
    (±5%, the acceptance pin) — exact declared evidence first, sampled
    apportionment of the remainder, residual to unattributed."""

    def _sum(self, buckets):
        return sum(buckets.values())

    def test_declared_frame_cause_is_exact(self, fp):
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("wal_fsync_wait", cause="io_wait"):
                fp.clock.advance(0.4)
        fp.close("f1")
        causes = fp.causes_snapshot()
        b = causes["wal_fsync_wait"]
        assert b["io_wait"] == pytest.approx(0.4)
        assert self._sum(b) == pytest.approx(0.4)

    def test_contended_timed_rlock_feeds_exact_lock_wait(self):
        """Satellite pin: the SMM lock's contended acquire declares its
        wait as lock_wait cause evidence — the lock_wait phase bucket
        conserves to the phase total within 5% with NO sampler help."""
        prof = FlowProfiler()
        prof.enable()
        lock = prof.timed_rlock()
        acct = prof.open("f1", "test.Flow")
        lock.acquire()

        def waiter():
            with prof.activate(acct):
                lock.acquire()
                lock.release()

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.15)
        lock.release()
        t.join(timeout=5)
        prof.close("f1")
        total = sum(
            agg["phases"]["lock_wait"]
            for agg in prof.snapshot()["classes"].values()
        )
        assert total >= 0.1
        b = prof.causes_snapshot()["lock_wait"]
        assert b["lock_wait"] == pytest.approx(total, rel=0.05)
        assert self._sum(b) == pytest.approx(total, rel=0.05)

    def test_exact_evidence_clamped_to_phase_total(self, fp):
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("host_verify"):
                fp.clock.advance(0.5)
        fp.close("f1")
        # over-declared exact evidence (10s against a 0.5s phase) must
        # scale down, never inflate the buckets past the total
        fp.note_cause_seconds("host_verify", "io_wait", 10.0)
        b = fp.causes_snapshot()["host_verify"]
        assert b["io_wait"] == pytest.approx(0.5)
        assert self._sum(b) == pytest.approx(0.5)

    def test_sampled_weights_apportion_the_remainder(self, fp):
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("host_verify"):
                fp.clock.advance(1.0)
        fp.close("f1")
        fp.note_cause_sample("host_verify", "on_cpu", 3.0)
        fp.note_cause_sample("host_verify", "gil_runnable", 1.0)
        b = fp.causes_snapshot()["host_verify"]
        assert b["on_cpu"] == pytest.approx(0.75)
        assert b["gil_runnable"] == pytest.approx(0.25)
        assert self._sum(b) == pytest.approx(1.0)

    def test_no_evidence_lands_in_unattributed(self, fp):
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("serialize"):
                fp.clock.advance(0.3)
        fp.close("f1")
        b = fp.causes_snapshot()["serialize"]
        assert b["unattributed"] == pytest.approx(0.3)

    def test_mixed_evidence_conserves_per_phase(self, fp):
        """Exact + sampled + residual together: every phase's buckets
        sum to its total within 5%."""
        acct = fp.open("f1", "test.Flow")
        with fp.activate(acct):
            with fp.frame("wal_fsync_wait", cause="io_wait"):
                fp.clock.advance(0.2)
            with fp.frame("host_verify"):
                fp.clock.advance(0.6)
            with fp.frame("serialize"):
                fp.clock.advance(0.1)
        fp.close("f1")
        fp.note_cause_seconds("host_verify", "lock_wait", 0.2)
        fp.note_cause_sample("host_verify", "on_cpu", 5.0)
        causes = fp.causes_snapshot()
        totals = {"wal_fsync_wait": 0.2, "host_verify": 0.6,
                  "serialize": 0.1}
        for phase, total in totals.items():
            assert self._sum(causes[phase]) == pytest.approx(
                total, rel=0.05), phase
        # exact evidence first, sampled weights take the remainder
        assert causes["host_verify"]["lock_wait"] == pytest.approx(0.2)
        assert causes["host_verify"]["on_cpu"] == pytest.approx(0.4)


# --------------------------------------------- the sampler's classifier

class TestClassifier:
    def test_auto_on_iff_contention_active(self):
        from corda_tpu.observability.contention import (
            configure_contention,
        )

        try:
            configure_contention(enabled=True, patch=False)
            s = StackSampler(hz=100)
            s.start()
            try:
                assert s._classify is True
            finally:
                s.stop()
        finally:
            configure_contention(enabled=False, patch=False)
        s = StackSampler(hz=100)
        s.start()
        try:
            assert s._classify is False
        finally:
            s.stop()

    def test_blocked_worker_classifies_lock_wait(self):
        s = StackSampler(hz=100)
        s._classify = True
        stop = threading.Event()
        t = threading.Thread(target=lambda: stop.wait(5),
                             name="flow-worker-41", daemon=True)
        t.start()
        try:
            time.sleep(0.05)
            s.sample_once()
            causes = s.dump()["causes"]
            assert causes["flow_worker"]["lock_wait"] >= 1.0
        finally:
            stop.set()
            t.join(timeout=5)

    def test_runnable_workers_split_the_gil(self):
        """k runnable threads split each tick 1/k on-cpu, (k−1)/k
        gil-runnable — each runnable thread still books exactly one
        sample's worth of weight in total."""
        s = StackSampler(hz=100)
        s._classify = True
        stop = threading.Event()

        def busy():
            x = 0
            while not stop.is_set():
                x = (x + 1) % 1000003

        workers = [
            threading.Thread(target=busy, name=f"flow-worker-{i}",
                             daemon=True)
            for i in range(2)
        ]
        for w in workers:
            w.start()
        try:
            time.sleep(0.05)
            s.sample_once()
            causes = s.dump()["causes"]["flow_worker"]
            assert causes.get("on_cpu", 0.0) > 0.0
            assert causes.get("gil_runnable", 0.0) > 0.0
            assert sum(causes.values()) == pytest.approx(2.0, abs=0.01)
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=5)

    def test_classified_weights_feed_flowprof_phases(self):
        """The thread→phase map routes a classified sample to the phase
        the thread is inside — the bridge from sampler to cause ledger."""
        configure_flowprof(enabled=True, reset=True)
        prof = flowprof()
        s = StackSampler(hz=100)
        s._classify = True
        stop = threading.Event()
        acct = prof.open("f1", "test.Flow")

        def worker():
            with prof.activate(acct):
                with prof.frame("host_verify"):
                    stop.wait(5)

        t = threading.Thread(target=worker, name="flow-worker-7",
                             daemon=True)
        t.start()
        try:
            time.sleep(0.05)
            assert prof.thread_phase(t.ident) == "host_verify"
            s.sample_once()
            assert prof._cause_samples["host_verify"]["lock_wait"] >= 1.0
        finally:
            stop.set()
            t.join(timeout=5)
            prof.close("f1")
            configure_flowprof(enabled=False, reset=True)


class TestClassifierOverhead:
    def test_real_thread_overhead_under_budget_with_classifier(self):
        """Satellite re-pin: the <3% sampling budget HOLDS with the
        blocked/running classifier on — same shape as the classifier-off
        budget test, classification forced via the public override."""
        stop = threading.Event()

        def busy():
            x = 0
            while not stop.is_set():
                x = (x + 1) % 1000003

        workers = [
            threading.Thread(target=busy, name=f"flow-worker-{i}",
                             daemon=True)
            for i in range(3)
        ]
        for w in workers:
            w.start()
        s = StackSampler(hz=100)
        s._classify_cfg = True
        s.start()
        try:
            time.sleep(0.8)
            ratio = s.overhead_ratio()
            dump = s.dump(top_n=10)
        finally:
            s.stop()
            stop.set()
            for w in workers:
                w.join(timeout=5)
        assert dump["classified"] is True
        assert dump["samples"] >= 20, dump["samples"]
        assert ratio < 0.03, f"classifying sampler {ratio:.4f} >= 3%"
        assert dump["causes"], "classifier on but no causes folded"
        assert "flow_worker" in dump["causes"]
