"""tpu-lint analyzer tests (ISSUE 6): each pass must flag its seeded
defect fixture, respect suppressions (inline + baseline), and the
runtime lockwatch sanitizer must detect a seeded A→B / B→A inversion.

The fixtures are scratch trees — the analyzer is pure AST, so a
three-line file with the defect is a complete test subject."""

import json
import os
import subprocess
import sys
import threading

import pytest

from corda_tpu.analysis import Project, get_passes, run_passes
from corda_tpu.analysis.core import split_suppressed

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANALYZE = os.path.join(REPO_ROOT, "tools_analyze.py")


def _scratch(tmp_path, files: dict) -> Project:
    root = tmp_path / "repo"
    for rel, text in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return Project(root)


def _findings(tmp_path, pass_id: str, files: dict):
    project = _scratch(tmp_path, files)
    findings = run_passes(project, get_passes([pass_id]))
    live, inline, baselined, stale = split_suppressed(project, findings, {})
    return live, inline


# ---------------------------------------------------------------- fixtures

LOCK_FIXTURE = """\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._count = 0

    def locked_add(self, x):
        with self._lock:
            self._items.append(x)
            self._count += 1

    def racy_add(self, x):
        self._items.append(x)

    def fine_locked(self):
        self._count -= 1
"""

DONATION_FIXTURE = """\
import functools
import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def donated(x):
    return x * 2

def bad(buf):
    out = donated(buf)
    return buf.sum() + out

def good(buf):
    buf = donated(buf)
    return buf.sum()

def branchy(buf, on_tpu):
    if on_tpu:
        return donated(buf)
    return buf.sum()

def same_line(buf, pair):
    return pair(donated(buf), buf)

def ternary(buf, fast):
    return donated(buf) if fast else buf.sum()
"""

HOTPATH_FIXTURE = """\
import numpy as np

def dispatch(pending):
    mask = pending.mask
    mask.block_until_ready()
    return np.asarray(mask)
"""

ENGINE_WORKER_FIXTURE = """\
import socket
import threading
import time

class StateMachineManager:
    def _worker_loop(self):
        while True:
            time.sleep(0.05)

    def _start_timer_locked(self, deadline):
        def loop():
            time.sleep(deadline)
        threading.Thread(target=loop, daemon=True).start()

class _FlowExecutor:
    def _run_body(self, flow):
        time.sleep(0.1)
        conn = socket.create_connection(("peer", 10003))
        return conn.recv(4096)
"""

THREAD_FIXTURE = """\
import threading

def fire_and_forget(fn):
    t = threading.Thread(target=fn)
    t.start()

def joined(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()

def daemonized(fn):
    threading.Thread(target=fn, daemon=True).start()

def explicit_nondaemon(fn):
    t = threading.Thread(target=fn)
    t.daemon = False
    t.start()
"""

ROLLBACK_FIXTURE = """\
def walk(pending):
    try:
        pending.collect()
    except Exception as e:
        pending.abort()
        raise

def walk_right(pending):
    try:
        pending.collect()
    except BaseException as e:
        pending.abort()
        raise
"""

ACK_ORDER_FIXTURE = """\
class Notary:
    def bad_commit(self, fut, rec):
        fut.set_result(rec)
        self._wal.append(rec)
        self._wal.flush()

    def good_commit(self, fut, rec):
        self._wal.append(rec)
        self._wal.flush()
        fut.set_result(rec)

    def ack_without_wal_work(self, fut, rec):
        fut.set_result(rec)

    def list_append_is_not_wal(self, fut, rec):
        fut.set_result(rec)
        self._pending.append(rec)

    def bare_ack_before_store_flush(self, ack, rec):
        ack()
        self._store.flush()
"""


class TestPasses:
    def test_lock_discipline_flags_outside_lock_write(self, tmp_path):
        live, _ = _findings(
            tmp_path, "lock-discipline", {"corda_tpu/box.py": LOCK_FIXTURE}
        )
        # _items: mutated under the lock in locked_add, outside in
        # racy_add; _count's outside write is in a *_locked method
        # (held-by-convention), so only ONE finding
        assert len(live) == 1
        f = live[0]
        assert f.pass_id == "lock-discipline"
        assert "_items" in f.message and "racy_add" in f.message

    def test_lock_discipline_respects_inline_suppression(self, tmp_path):
        fixed = LOCK_FIXTURE.replace(
            "    def racy_add(self, x):\n        self._items.append(x)",
            "    def racy_add(self, x):\n"
            "        # tpu-lint: allow=lock-discipline single-writer\n"
            "        self._items.append(x)",
        )
        live, inline = _findings(
            tmp_path, "lock-discipline", {"corda_tpu/box.py": fixed}
        )
        assert live == []
        assert len(inline) == 1

    def test_donation_flags_post_donation_read(self, tmp_path):
        live, _ = _findings(
            tmp_path, "donation-safety", {"corda_tpu/k.py": DONATION_FIXTURE}
        )
        # bad() reads after donation; same_line() re-passes the donated
        # buffer ON the donating line (evaluation order still puts the
        # read after the donation). good() rebinds; branchy()/ternary()
        # read on the mutually-exclusive non-donating arm.
        assert {f.key.split("::")[1] for f in live} == {"bad", "same_line"}
        assert all("buf" in f.message for f in live)

    def test_donation_respects_suppression(self, tmp_path):
        fixed = DONATION_FIXTURE.replace(
            "    return buf.sum() + out",
            "    return buf.sum() + out  # tpu-lint: allow=donation-safety",
        )
        live, inline = _findings(
            tmp_path, "donation-safety", {"corda_tpu/k.py": fixed}
        )
        # bad() suppressed inline; same_line() still live
        assert len(inline) == 1
        assert [f.key.split("::")[1] for f in live] == ["same_line"]

    def test_hotpath_flags_readback_in_hot_file_only(self, tmp_path):
        files = {
            "corda_tpu/serving/scheduler.py": HOTPATH_FIXTURE,
            "corda_tpu/cold.py": HOTPATH_FIXTURE,  # not a hot file: clean
        }
        live, _ = _findings(tmp_path, "hot-path-blocking", files)
        assert {f.file for f in live} == {"corda_tpu/serving/scheduler.py"}
        kinds = {f.message.split(" in ")[0] for f in live}
        assert any("block_until_ready" in k for k in kinds)
        assert any("asarray" in k for k in kinds)

    def test_hotpath_respects_suppression(self, tmp_path):
        fixed = HOTPATH_FIXTURE.replace(
            "    mask.block_until_ready()",
            "    # tpu-lint: allow=hot-path-blocking measured sync point\n"
            "    mask.block_until_ready()",
        ).replace(
            "    return np.asarray(mask)",
            "    return np.asarray(mask)  # tpu-lint: allow=hot-path-blocking",
        )
        live, inline = _findings(
            tmp_path, "hot-path-blocking",
            {"corda_tpu/serving/scheduler.py": fixed},
        )
        assert live == [] and len(inline) == 2

    def test_hotpath_flags_blocking_in_engine_worker_scope(self, tmp_path):
        live, _ = _findings(
            tmp_path, "hot-path-blocking",
            {"corda_tpu/flows/engine.py": ENGINE_WORKER_FIXTURE},
        )
        # _worker_loop's sleep + _run_body's sleep/create_connection/
        # .recv() — the timer thread's nested `loop` sleep is OUTSIDE
        # worker scope (dedicated sleep-timer thread) and stays legal
        assert len(live) == 4, [f.render() for f in live]
        assert all("worker-pool scope" in f.message for f in live)
        scopes = {f.key.split("::")[1] for f in live}
        assert scopes == {
            "StateMachineManager._worker_loop",
            "_FlowExecutor._run_body",
        }
        kinds = {f.key.split("::")[2] for f in live}
        assert kinds == {
            "time.sleep()", "socket.create_connection()", ".recv()",
        }

    def test_hotpath_worker_scope_is_engine_file_only(self, tmp_path):
        # the same code anywhere else is not the worker pool's business
        live, _ = _findings(
            tmp_path, "hot-path-blocking",
            {"corda_tpu/flows/other.py": ENGINE_WORKER_FIXTURE},
        )
        assert live == []

    def test_hotpath_worker_scope_respects_suppression(self, tmp_path):
        fixed = ENGINE_WORKER_FIXTURE.replace(
            "    def _worker_loop(self):\n"
            "        while True:\n"
            "            time.sleep(0.05)",
            "    def _worker_loop(self):\n"
            "        while True:\n"
            "            # tpu-lint: allow=hot-path-blocking drain poll\n"
            "            time.sleep(0.05)",
        )
        live, inline = _findings(
            tmp_path, "hot-path-blocking",
            {"corda_tpu/flows/engine.py": fixed},
        )
        assert len(inline) == 1
        assert {f.key.split("::")[1] for f in live} == \
            {"_FlowExecutor._run_body"}

    def test_thread_lifecycle_flags_unjoined_nondaemon(self, tmp_path):
        live, _ = _findings(
            tmp_path, "thread-lifecycle", {"corda_tpu/t.py": THREAD_FIXTURE}
        )
        # fire_and_forget never daemonizes/joins; explicit_nondaemon's
        # `t.daemon = False` is a non-daemon declaration, not a pass
        assert len(live) == 2
        msgs = " ".join(f.message for f in live)
        assert "fire_and_forget" in msgs and "explicit_nondaemon" in msgs

    def test_thread_lifecycle_respects_suppression(self, tmp_path):
        fixed = THREAD_FIXTURE.replace(
            "    t = threading.Thread(target=fn)\n    t.start()\n\ndef joined",
            "    # tpu-lint: allow=thread-lifecycle short-lived\n"
            "    t = threading.Thread(target=fn)\n    t.start()\n\ndef joined",
        )
        live, inline = _findings(
            tmp_path, "thread-lifecycle", {"corda_tpu/t.py": fixed}
        )
        # fire_and_forget suppressed inline; explicit_nondaemon still live
        assert len(inline) == 1
        assert [f.key.split("::")[1] for f in live] == ["explicit_nondaemon"]

    def test_ack_order_flags_ack_before_wal_only(self, tmp_path):
        live, _ = _findings(
            tmp_path, "durability-ack-order",
            {"corda_tpu/notary/svc.py": ACK_ORDER_FIXTURE},
        )
        # bad_commit (future before wal append) + the bare-ack-before-
        # store-flush shape; good ordering, ack-only paths, and list
        # .append receivers stay clean
        assert len(live) == 2, [f.render() for f in live]
        assert {"bad_commit" in f.message or
                "bare_ack_before_store_flush" in f.message
                for f in live} == {True}
        assert all(f.pass_id == "durability-ack-order" for f in live)

    def test_ack_order_out_of_scope_file_is_clean(self, tmp_path):
        # same defect outside the notary/flows/durability commit paths:
        # not this pass's business
        live, _ = _findings(
            tmp_path, "durability-ack-order",
            {"corda_tpu/serving/svc.py": ACK_ORDER_FIXTURE},
        )
        assert live == []

    def test_ack_order_respects_inline_suppression(self, tmp_path):
        fixed = ACK_ORDER_FIXTURE.replace(
            "    def bad_commit(self, fut, rec):\n        fut.set_result(rec)",
            "    def bad_commit(self, fut, rec):\n"
            "        # tpu-lint: allow=durability-ack-order legacy path\n"
            "        fut.set_result(rec)",
        ).replace(
            "    def bare_ack_before_store_flush(self, ack, rec):\n"
            "        ack()",
            "    def bare_ack_before_store_flush(self, ack, rec):\n"
            "        # tpu-lint: allow=durability-ack-order legacy path\n"
            "        ack()",
        )
        live, inline = _findings(
            tmp_path, "durability-ack-order",
            {"corda_tpu/notary/svc.py": fixed},
        )
        assert live == []
        assert len(inline) == 2

    def test_rollback_flags_narrow_catch(self, tmp_path):
        live, _ = _findings(
            tmp_path, "swallowed-rollback", {"corda_tpu/r.py": ROLLBACK_FIXTURE}
        )
        assert len(live) == 1
        assert "walk" in live[0].key and "walk_right" not in live[0].key
        assert "BaseException" in live[0].message

    def test_fault_sites_cross_check_both_ways(self, tmp_path):
        files = {
            "corda_tpu/x.py": 'check_site("alpha.op")\n',
            "docs/FAULT_INJECTION.md": (
                "## Fault sites\n\n"
                "| Site | What |\n|---|---|\n"
                "| `beta.op` | gone |\n"
            ),
        }
        live, _ = _findings(tmp_path, "fault-sites", files)
        keys = {f.key for f in live}
        assert "site::alpha.op" in keys        # in code, not documented
        assert "stale-site::beta.op" in keys   # documented, not in code


class TestDriver:
    """The CLI: green tree exits 0 fast; defects and stale baseline
    entries exit 1."""

    def _run(self, *args):
        return subprocess.run(
            [sys.executable, ANALYZE, *args],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )

    def test_defect_tree_fails_with_finding(self, tmp_path):
        root = tmp_path / "repo"
        (root / "corda_tpu").mkdir(parents=True)
        (root / "corda_tpu" / "t.py").write_text(THREAD_FIXTURE)
        proc = self._run("--root", str(root),
                         "--passes", "thread-lifecycle")
        assert proc.returncode == 1
        assert "thread-lifecycle" in proc.stdout
        assert "fire_and_forget" in proc.stdout

    def test_stale_baseline_entry_fails(self, tmp_path):
        root = tmp_path / "repo"
        (root / "corda_tpu").mkdir(parents=True)
        (root / "corda_tpu" / "ok.py").write_text("x = 1\n")
        (root / "ANALYSIS_BASELINE.json").write_text(json.dumps({
            "schema": 1,
            "suppress": [{"pass": "thread-lifecycle",
                          "key": "corda_tpu/gone.py::f::t",
                          "reason": "stale"}],
        }))
        proc = self._run("--root", str(root))
        assert proc.returncode == 1
        assert "STALE" in proc.stdout

    def test_baseline_suppresses_matching_finding(self, tmp_path):
        root = tmp_path / "repo"
        (root / "corda_tpu").mkdir(parents=True)
        (root / "corda_tpu" / "t.py").write_text(THREAD_FIXTURE)
        # learn the stable keys from a verbose failing run, baseline them
        probe = self._run("--root", str(root),
                          "--passes", "thread-lifecycle", "-v")
        keys = [
            line.split("key:", 1)[1].strip()
            for line in probe.stdout.splitlines() if "key:" in line
        ]
        assert keys
        (root / "ANALYSIS_BASELINE.json").write_text(json.dumps({
            "schema": 1,
            "suppress": [{"pass": "thread-lifecycle", "key": k,
                          "reason": "fixture"} for k in keys],
        }))
        proc = self._run("--root", str(root),
                         "--passes", "thread-lifecycle")
        assert proc.returncode == 0, proc.stdout
        assert f"{len(keys)} baselined" in proc.stdout


class TestLockwatch:
    """The runtime half: the lock-order sanitizer sees the acquisition
    graph the static passes cannot."""

    def setup_method(self):
        from corda_tpu.observability import lockwatch

        lockwatch.reset()

    def teardown_method(self):
        from corda_tpu.observability import lockwatch

        lockwatch.uninstall()
        lockwatch.reset()

    def test_seeded_inversion_detected(self):
        from corda_tpu.observability.lockwatch import (
            WatchedLock,
            cycle_report,
        )

        a = WatchedLock(name="A")
        b = WatchedLock(name="B")
        # the inversion does not need to deadlock to be found — the two
        # orders just both have to happen (even on one thread)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        report = cycle_report()
        assert len(report) == 1
        assert set(report[0]["cycle"]) == {"A", "B"}
        edges = {(e["from"], e["to"]) for e in report[0]["edges"]}
        assert ("A", "B") in edges and ("B", "A") in edges
        # the report carries the acquisition stack for the human
        assert any(e["stack"] for e in report[0]["edges"])

    def test_consistent_order_is_clean(self):
        from corda_tpu.observability.lockwatch import (
            WatchedLock,
            cycle_report,
        )

        a = WatchedLock(name="A")
        b = WatchedLock(name="B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert cycle_report() == []

    def test_inversion_across_threads(self):
        from corda_tpu.observability.lockwatch import (
            WatchedLock,
            cycle_report,
        )

        a = WatchedLock(name="A")
        b = WatchedLock(name="B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
        assert len(cycle_report()) == 1

    def test_reentrant_hold_is_not_an_edge(self):
        from corda_tpu.observability.lockwatch import (
            WatchedLock,
            cycle_report,
            lockwatch_edges,
        )

        r = WatchedLock(name="R", reentrant=True)
        with r:
            with r:
                pass
        assert lockwatch_edges() == {}
        assert cycle_report() == []

    def test_same_site_instances_lenient_vs_strict(self):
        from corda_tpu.observability.lockwatch import (
            WatchedLock,
            cycle_report,
        )

        x = WatchedLock(name="pool")
        y = WatchedLock(name="pool")
        with x:
            with y:
                pass
        # two instances of one lock class nested: invisible unless
        # strict (per-instance order needs a key the watcher can't guess)
        assert cycle_report() == []
        assert len(cycle_report(strict=True)) == 1

    def test_install_watches_new_locks_and_condition(self):
        from corda_tpu.observability import lockwatch

        lockwatch.install()
        try:
            assert lockwatch.installed()
            lk = threading.Lock()
            assert isinstance(lk, lockwatch.WatchedLock)
            cond = threading.Condition()
            # the Condition wait/notify protocol must work over the
            # watched lock (duck-typed _release_save/_acquire_restore)
            got: list = []

            def waiter():
                with cond:
                    got.append(cond.wait(timeout=5))

            t = threading.Thread(target=waiter)
            t.start()
            import time

            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                with cond:
                    cond.notify_all()
                if got:
                    break
                time.sleep(0.01)
            t.join(timeout=5)
            assert got == [True]
        finally:
            lockwatch.uninstall()
        assert threading.Lock is not lockwatch.WatchedLock

    def test_install_survives_fresh_stdlib_imports(self):
        """Regression: concurrent.futures.thread (imported FRESH after
        install) calls `_at_fork_reinit` on its module-level lock at
        import time — the watched wrapper must honor the whole stdlib
        lock surface. Needs a subprocess: in this process the module is
        long imported."""
        code = (
            f"import sys; sys.path.insert(0, {REPO_ROOT!r})\n"
            "from corda_tpu.observability import lockwatch\n"
            "lockwatch.install()\n"
            "from concurrent.futures import ThreadPoolExecutor\n"
            "ex = ThreadPoolExecutor(2)\n"
            "assert ex.submit(lambda: 41 + 1).result(timeout=10) == 42\n"
            "ex.shutdown()\n"
            "print('fresh-import ok')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "fresh-import ok" in proc.stdout

    def test_uninstall_restores_factories(self):
        from corda_tpu.observability import lockwatch

        real = threading.Lock
        lockwatch.install()
        lockwatch.uninstall()
        assert threading.Lock is real


class TestAnalysisSelfCheck:
    def test_passes_have_unique_ids_and_docs(self):
        from corda_tpu.analysis import ALL_PASSES

        ids = [p.id for p in ALL_PASSES]
        assert len(ids) == len(set(ids))
        assert all(p.doc for p in ALL_PASSES)
        # the five tentpole passes + the two folded registry passes +
        # the durability ack-order pass (ISSUE 10)
        assert set(ids) == {
            "lock-discipline", "donation-safety", "hot-path-blocking",
            "thread-lifecycle", "swallowed-rollback", "metrics-doc",
            "fault-sites", "durability-ack-order",
        }

    def test_unknown_pass_id_raises(self):
        from corda_tpu.analysis import get_passes

        with pytest.raises(KeyError):
            get_passes(["nonsense-pass"])
