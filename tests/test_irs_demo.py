"""IRS lifecycle sample tests (r3 VERDICT missing #1 / task 5).

Three tiers, mirroring the reference's IRSTests.kt + IRSDemoTest.kt:
contract-clause unit tests over hand-built LedgerTransactions, the
deterministic mocknet lifecycle under an injected clock (the end-to-end
``SchedulableState`` → scheduler → flow → oracle → notarise chain no other
test exercises), and the driver tier — real node processes whose own
schedulers run every fixing to maturity, reached only via RPC.
"""

import hashlib
import time

import pytest
from conftest import node_process_capability

from corda_tpu.crypto import SecureHash, generate_keypair
from corda_tpu.ledger import (
    Command,
    CordaX500Name,
    Party,
    StateAndRef,
    StateRef,
    TransactionState,
)
from corda_tpu.ledger.ledger_tx import LedgerTransaction
from corda_tpu.ledger.states import TransactionVerificationException
from corda_tpu.samples.irs_demo import (
    IRS_PROGRAM_ID,
    UNFIXED,
    Agree,
    FixingRoleDecider,
    IRSDealFlow,
    IRSState,
    InterestRateSwap,
    Mature,
    Refix,
    make_irs,
)
from corda_tpu.samples.oracle_demo import Fix, FixOf, RatesOracle


def _party(name: str) -> Party:
    return Party(
        CordaX500Name(name, "London", "GB"), generate_keypair().public
    )


@pytest.fixture(scope="module")
def parties():
    return _party("Fixed Payer"), _party("Floating Payer"), _party("Oracle")


def _deal(parties, **kw) -> IRSState:
    fixed, floating, oracle = parties
    kw.setdefault("t0", 1000.0)
    kw.setdefault("n_periods", 2)
    return make_irs(fixed, floating, oracle, **kw)


def _ltx(ins, outs, cmds, notary=None):
    txid = SecureHash(hashlib.sha256(b"irs-test").digest())
    prev = SecureHash(hashlib.sha256(b"irs-prev").digest())
    return LedgerTransaction(
        tx_id=txid,
        inputs=tuple(
            StateAndRef(
                TransactionState(s, IRS_PROGRAM_ID, notary), StateRef(prev, i)
            )
            for i, s in enumerate(ins)
        ),
        outputs=tuple(
            TransactionState(s, IRS_PROGRAM_ID, notary) for s in outs
        ),
        commands=tuple(cmds),
        attachments=(),
        notary=notary,
        time_window=None,
    )


class TestIRSContract:
    """Clause checks (reference: IRSTests.kt over IRS.kt:491-557)."""

    def test_agree_accepts(self, parties):
        deal = _deal(parties)
        tx = _ltx([], [deal], [Command(
            Agree(),
            (deal.fixed_rate_payer.owning_key,
             deal.floating_rate_payer.owning_key),
        )])
        InterestRateSwap().verify(tx)

    def test_agree_missing_signer_rejected(self, parties):
        deal = _deal(parties)
        tx = _ltx([], [deal], [Command(
            Agree(), (deal.fixed_rate_payer.owning_key,)
        )])
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(tx)

    def test_agree_prefixed_floating_rejected(self, parties):
        deal = _deal(parties)
        bad = deal.with_fix(0, 123)  # floating leg must start unfixed
        tx = _ltx([], [bad], [Command(
            Agree(),
            (deal.fixed_rate_payer.owning_key,
             deal.floating_rate_payer.owning_key),
        )])
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(tx)

    def _refix_tx(self, deal, new_deal, fix, oracle_key=None,
                  participants=None):
        parts = participants or (
            deal.fixed_rate_payer.owning_key,
            deal.floating_rate_payer.owning_key,
        )
        return _ltx([deal], [new_deal], [
            Command(Refix(), parts),
            Command(fix, (oracle_key or deal.oracle.owning_key,)),
        ])

    def test_refix_accepts(self, parties):
        deal = _deal(parties)
        ev = deal.floating_schedule[0]
        fix = Fix(FixOf(deal.index_name, ev.index_date, deal.index_tenor),
                  162)
        InterestRateSwap().verify(
            self._refix_tx(deal, deal.with_fix(0, 162), fix)
        )

    def test_refix_wrong_rate_rejected(self, parties):
        deal = _deal(parties)
        ev = deal.floating_schedule[0]
        fix = Fix(FixOf(deal.index_name, ev.index_date, deal.index_tenor),
                  162)
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(
                self._refix_tx(deal, deal.with_fix(0, 999), fix)
            )

    def test_refix_out_of_order_rejected(self, parties):
        deal = _deal(parties)
        ev = deal.floating_schedule[1]
        fix = Fix(FixOf(deal.index_name, ev.index_date, deal.index_tenor),
                  162)
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(
                self._refix_tx(deal, deal.with_fix(1, 162), fix)
            )

    def test_refix_without_oracle_signer_rejected(self, parties):
        deal = _deal(parties)
        ev = deal.floating_schedule[0]
        fix = Fix(FixOf(deal.index_name, ev.index_date, deal.index_tenor),
                  162)
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(self._refix_tx(
                deal, deal.with_fix(0, 162), fix,
                oracle_key=deal.fixed_rate_payer.owning_key,
            ))

    def test_refix_tampering_other_fields_rejected(self, parties):
        import dataclasses

        deal = _deal(parties)
        ev = deal.floating_schedule[0]
        fix = Fix(FixOf(deal.index_name, ev.index_date, deal.index_tenor),
                  162)
        bad = dataclasses.replace(
            deal.with_fix(0, 162), notional=deal.notional * 2
        )
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(self._refix_tx(deal, bad, fix))

    def test_refix_truncating_schedule_rejected(self, parties):
        """A refix must not drop trailing floating events — zip-based
        diffing would otherwise let a deal mature while skipping
        contractual payment periods (found by adversarial review r4)."""
        import dataclasses

        deal = _deal(parties, n_periods=4)
        ev = deal.floating_schedule[0]
        fix = Fix(FixOf(deal.index_name, ev.index_date, deal.index_tenor),
                  162)
        shrunk = dataclasses.replace(
            deal.with_fix(0, 162),
            floating_schedule=deal.with_fix(0, 162).floating_schedule[:2],
            fixed_schedule=deal.fixed_schedule[:2],
        )
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(self._refix_tx(deal, shrunk, fix))
        grown = dataclasses.replace(
            deal.with_fix(0, 162),
            floating_schedule=deal.with_fix(0, 162).floating_schedule
            + (deal.floating_schedule[-1],),
        )
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(self._refix_tx(deal, grown, fix))

    def test_mature_accepts_only_fully_fixed(self, parties):
        deal = _deal(parties)
        both = (deal.fixed_rate_payer.owning_key,
                deal.floating_rate_payer.owning_key)
        with pytest.raises(TransactionVerificationException):
            InterestRateSwap().verify(
                _ltx([deal], [], [Command(Mature(), both)])
            )
        fixed = deal.with_fix(0, 150).with_fix(1, 157)
        InterestRateSwap().verify(
            _ltx([fixed], [], [Command(Mature(), both)])
        )

    def test_net_payments_report(self, parties):
        deal = _deal(parties).with_fix(0, 150).with_fix(1, 190)
        rows = deal.net_payments()
        # fixed 170bp vs floating 150/190bp on 25M over 90/360 days
        assert rows[0]["net_from_fixed_payer"] > 0  # fixed payer receives
        assert rows[1]["net_from_fixed_payer"] < 0
        assert rows[0]["fixed"] == 25_000_000 * 170 * 90 // (360 * 10_000)


class TestScheduledLifecycle:
    """The chain no other test drives: recording a SchedulableState arms
    the scheduler, whose wakeups run fixings through the oracle tear-off
    to maturity (reference: FixingFlow.kt:116-143 over
    NodeSchedulerService)."""

    def test_fixings_to_maturity_under_virtual_clock(self):
        from corda_tpu.testing import MockNetworkNodes

        now = [1000.0]
        clock = lambda: now[0]  # noqa: E731
        with MockNetworkNodes() as net:
            a = net.create_node("Bank A", clock=clock)
            b = net.create_node("Bank B", clock=clock)
            on = net.create_node("Rates Oracle", clock=clock)
            notary = net.create_notary_node("Notary")
            oracle = RatesOracle(on.party, on.keypair)
            on.services.oracle = oracle

            deal = make_irs(
                a.party, b.party, on.party, n_periods=3, t0=1000.0,
                period_s=10.0,
            )
            rates = {}
            for i, ev in enumerate(deal.floating_schedule):
                of = FixOf(deal.index_name, ev.index_date, deal.index_tenor)
                rates[of] = 150 + 9 * i
                oracle.add_rate(of, rates[of])
            a.run_flow(IRSDealFlow(b.party, notary.party, deal))

            # before the fixing time nothing fires
            assert a.scheduler.pump() == 0 and b.scheduler.pump() == 0

            def pump_until(done, timeout_s=30.0):
                """Advance the virtual-clock schedulers; message delivery
                and flow execution run in real time underneath."""
                deadline = time.monotonic() + timeout_s
                while time.monotonic() < deadline:
                    a.scheduler.pump()
                    b.scheduler.pump()
                    if done():
                        return True
                    time.sleep(0.02)
                return False

            def fixed_count(node):
                live = node.services.vault_service.unconsumed_states(
                    IRSState
                )
                if len(live) != 1:
                    return -1
                return sum(
                    1 for ev in live[0].state.data.floating_schedule
                    if ev.is_fixed
                )

            for period in range(3):
                now[0] = 1000.0 + (period + 0.6) * 10.0
                assert pump_until(
                    lambda: fixed_count(a) == period + 1
                    and fixed_count(b) == period + 1
                ), f"fixing {period} did not land on both nodes"
                live = a.services.vault_service.unconsumed_states(IRSState)
                sched = live[0].state.data.floating_schedule
                assert [ev.rate_bp for ev in sched[: period + 1]] == [
                    150 + 9 * i for i in range(period + 1)
                ]
                assert all(ev.rate_bp == UNFIXED
                           for ev in sched[period + 1:])
                # the counterparty converges to the same deal state
                live_b = b.services.vault_service.unconsumed_states(IRSState)
                assert live_b[0].state.data == live[0].state.data

            # past maturity: the deal is consumed on BOTH nodes
            now[0] = 1000.0 + 3.6 * 10.0
            assert pump_until(
                lambda: not a.services.vault_service.unconsumed_states(
                    IRSState
                ) and not b.services.vault_service.unconsumed_states(
                    IRSState
                )
            ), "deal did not mature on both nodes"

    def test_restart_rearms_schedule_from_vault(self):
        """A fresh scheduler observing an existing vault re-derives the
        pending fixing (the node-restart path, scheduler.py snapshot)."""
        from corda_tpu.node.scheduler import NodeSchedulerService
        from corda_tpu.testing import MockNetworkNodes

        now = [1000.0]
        with MockNetworkNodes() as net:
            a = net.create_node("Bank A", clock=lambda: now[0])
            b = net.create_node("Bank B", clock=lambda: now[0])
            on = net.create_node("Rates Oracle", clock=lambda: now[0])
            notary = net.create_notary_node("Notary")
            on.services.oracle = RatesOracle(on.party, on.keypair)
            deal = make_irs(a.party, b.party, on.party, n_periods=1,
                            t0=1000.0, period_s=10.0)
            a.run_flow(IRSDealFlow(b.party, notary.party, deal))

            fired = []
            fresh = NodeSchedulerService(
                lambda path, args: fired.append((path, args)),
                clock=lambda: now[0],
            )
            fresh.observe_vault(a.services.vault_service)
            now[0] = 1006.0
            assert fresh.pump() == 1
            assert fired[0][0].endswith("FixingRoleDecider")


@pytest.mark.slow
class TestIRSDriver:
    """The VERDICT's done-bar: a driver-spawned two-dealer + oracle
    ensemble whose real node schedulers run every fixing to maturity,
    observed only via RPC (reference: IRSDemoTest.kt)."""

    # multi-process tier: skip (with the reason) when the environment
    # cannot bind sockets / spawn node subprocesses, instead of failing
    pytestmark = pytest.mark.skipif(
        bool(node_process_capability()),
        reason=node_process_capability() or "",
    )

    def test_scheduled_fixings_to_maturity(self, tmp_path):
        from conftest import require_driver_ensemble

        require_driver_ensemble()
        from corda_tpu.flows.api import class_path
        from corda_tpu.testing import driver

        apps = ("corda_tpu.finance", "corda_tpu.samples.irs_demo")
        with driver(str(tmp_path)) as dsl:
            dsl.start_node("O=Notary,L=Zurich,C=CH", notary=True,
                           cordapps=apps)
            dealer_a = dsl.start_node("O=Dealer A,L=London,C=GB",
                                      cordapps=apps)
            dealer_b = dsl.start_node("O=Dealer B,L=Rome,C=IT",
                                      cordapps=apps)
            oracle_n = dsl.start_node("O=Rates Oracle,L=Paris,C=FR",
                                      cordapps=apps)
            conn = dsl.rpc(dealer_a)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if (conn.proxy.notary_identities()
                        and len(conn.proxy.network_map_snapshot()) >= 4):
                    break
                time.sleep(0.3)
            notary = conn.proxy.notary_identities()[0]
            b_party = conn.proxy.well_known_party_from_x500_name(
                CordaX500Name.parse("O=Dealer B,L=Rome,C=IT")
            )
            oracle_party = conn.proxy.well_known_party_from_x500_name(
                CordaX500Name.parse("O=Rates Oracle,L=Paris,C=FR")
            )
            a_party = conn.proxy.node_info().legal_identity

            n_periods = 2
            deal = make_irs(
                a_party, b_party, oracle_party, n_periods=n_periods,
                period_s=1.5,
            )
            # load the oracle's curve over RPC (the reference's rate
            # upload API)
            oconn = dsl.rpc(oracle_n)
            fixes = tuple(
                Fix(FixOf(deal.index_name, ev.index_date, deal.index_tenor),
                    140 + 11 * i)
                for i, ev in enumerate(deal.floating_schedule)
            )
            fid = oconn.proxy.start_flow_dynamic(
                "corda_tpu.samples.irs_demo:AddRatesFlow", fixes
            )
            assert oconn.proxy.flow_result(fid, 30) == n_periods

            fid = conn.proxy.start_flow_dynamic(
                class_path(IRSDealFlow), b_party, notary, deal
            )
            conn.proxy.flow_result(fid, 60)

            # the node schedulers drive everything from here; wait until
            # both dealers' deals are consumed (matured)
            bconn = dsl.rpc(dealer_b)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (conn.proxy.vault_query_by().total_states_available == 0
                        and bconn.proxy.vault_query_by(
                        ).total_states_available == 0):
                    break
                time.sleep(0.4)
            assert conn.proxy.vault_query_by().total_states_available == 0
            assert bconn.proxy.vault_query_by().total_states_available == 0
            # every fixing + the maturity notarised as separate txs:
            # agree + n fixings + mature recorded on both dealers
            assert conn.proxy.transaction_count() >= n_periods + 2
            assert bconn.proxy.transaction_count() >= n_periods + 2
