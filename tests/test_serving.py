"""Serving-layer tests — the continuous-batching device scheduler
(corda_tpu/serving) and the verifier/notary/flow refactors that submit
through it (ISSUE 2 acceptance criteria).

Everything runs the host crypto path (use_device=False, or device
requests failed over by an injected fault before any kernel is touched),
so failures localize to the scheduling layer; the kernels have their own
differential suites.
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import Future

import pytest

from corda_tpu.crypto import generate_keypair, sign, sign_tx_id
from corda_tpu.faultinject import FaultInjector, FaultPlan
from corda_tpu.faultinject import clear as clear_injector
from corda_tpu.faultinject import install as install_injector
from corda_tpu.ledger import (
    CordaX500Name,
    Party,
    SignedTransaction,
    StateRef,
    TransactionBuilder,
)
from corda_tpu.ledger.states import register_contract
from corda_tpu.node.monitoring import monitoring_snapshot, node_metrics
from corda_tpu.serialization import register_custom
from corda_tpu.serving import (
    BULK,
    INTERACTIVE,
    SERVICE,
    DeadlineExceededError,
    DeviceScheduler,
    SchedulerClosedError,
    SchedulerSaturatedError,
    device_scheduler,
    load_shape_table,
    shape_table,
)
from corda_tpu.verifier import BatchedVerifierService, VerificationError
from corda_tpu.verifier.batch import InvalidSignatureError


# ------------------------------------------------------------- test ledger

@dataclasses.dataclass(frozen=True)
class TokenState:
    value: int

    @property
    def participants(self):
        return []


@dataclasses.dataclass(frozen=True)
class TokenCommand:
    op: str


register_custom(
    TokenState, "serving.TokenState",
    to_fields=lambda s: {"value": s.value},
    from_fields=lambda d: TokenState(d["value"]),
)
register_custom(
    TokenCommand, "serving.TokenCommand",
    to_fields=lambda c: {"op": c.op},
    from_fields=lambda d: TokenCommand(d["op"]),
)


@register_contract("serving.TokenContract")
class TokenContract:
    def verify(self, tx):
        if not tx.commands_of_type(TokenCommand):
            raise ValueError("no TokenCommand")


@pytest.fixture(scope="module")
def notary():
    kp = generate_keypair()
    return Party(CordaX500Name("ServingNotary", "Zurich", "CH"), kp.public), kp


@pytest.fixture(scope="module")
def alice():
    kp = generate_keypair()
    return Party(CordaX500Name("ServingAlice", "London", "GB"), kp.public), kp


def issue_tx(notary, alice, value=100) -> SignedTransaction:
    b = TransactionBuilder(notary=notary[0])
    b.add_output_state(TokenState(value), "serving.TokenContract")
    b.add_command(TokenCommand("issue"), alice[1].public)
    return b.sign_initial_transaction(alice[1])


def move_tx(notary, alice, parent, idx=0):
    b = TransactionBuilder(notary=notary[0])
    b._inputs.append(StateRef(parent.id, idx))
    b._ensure_attachment(parent.tx.outputs[idx].contract)
    b.add_output_state(
        TokenState(parent.tx.outputs[idx].data.value),
        "serving.TokenContract",
    )
    b.add_command(TokenCommand("move"), alice[1].public)
    wtx = b.to_wire_transaction()
    return SignedTransaction.create(wtx, [
        sign_tx_id(alice[1].private, alice[1].public, wtx.id),
        sign_tx_id(notary[1].private, notary[1].public, wtx.id),
    ])


def make_rows(n, tamper=()):
    kp = generate_keypair()
    rows = []
    for i in range(n):
        msg = b"serving-row-%d" % i
        s = sign(kp.private, msg)
        if i in tamper:
            s = b"\0" * len(s)
        rows.append((kp.public, s, msg))
    return rows


# ------------------------------------------------------------ shape table

class TestShapes:
    def test_checked_in_table_loads(self):
        t = shape_table()
        assert t.buckets == sorted(t.buckets)
        assert t.max_bucket >= 4096

    def test_bucket_for(self):
        t = shape_table()
        assert t.bucket_for(1) == t.buckets[0]
        assert t.bucket_for(t.buckets[0] + 1) == t.buckets[1]
        # floor hint (the notary's pinned window) dominates a small n
        assert t.bucket_for(3, floor=1024) == 1024
        # beyond the ladder: None → kernels fall back to pow2 padding
        assert t.bucket_for(t.max_bucket + 1) is None

    def test_corrupt_override_falls_back_to_default(self, tmp_path,
                                                    monkeypatch):
        bad = tmp_path / "shapes.json"
        bad.write_text("{not json")
        monkeypatch.setenv("CORDA_TPU_SERVING_SHAPES", str(bad))
        t = load_shape_table()
        assert t.buckets  # never raises, never empty

    def test_env_override_wins(self, tmp_path, monkeypatch):
        override = tmp_path / "shapes.json"
        override.write_text(json.dumps({"buckets": [64, 4096]}))
        monkeypatch.setenv("CORDA_TPU_SERVING_SHAPES", str(override))
        t = load_shape_table()
        assert t.buckets == [64, 4096]

    def test_block_sweep_shape_chooser(self):
        from tools_block_sweep import choose_serving_shapes

        results = {
            "captured_at": "now", "device": "test",
            "ed25519_block_128": {"sigs_per_sec_median": 100.0},
            "ed25519_block_256": {"error": "Mosaic"},
            "ecdsa_k1_block_64": {"error": "pallas"},
            "ecdsa_k1_block_128": {"sigs_per_sec_median": 50.0},
        }
        shapes = choose_serving_shapes(results)
        assert shapes["ed25519_block"] == 128
        assert shapes["ecdsa_block"] == 128
        assert shapes["buckets"][0] == 128
        assert shapes["buckets"][-1] == 8192
        # a fully-failed sweep must not overwrite the checked-in table
        assert choose_serving_shapes({"captured_at": "now"}) is None


# -------------------------------------------------------- scheduler core

class TestSchedulerCore:
    def test_single_request_idle_dispatches_immediately(self):
        """Acceptance: a single request on an idle scheduler dispatches
        without waiting out a batching window."""
        s = DeviceScheduler(use_device_default=False)
        try:
            rows = make_rows(1)
            t0 = time.monotonic()
            rr = s.submit_rows(rows, priority=INTERACTIVE).result(timeout=10)
            elapsed = time.monotonic() - t0
            assert rr.mask.tolist() == [True]
            # far below any plausible batching window (the old verifier
            # default was 5 ms, but services pin up to seconds)
            assert elapsed < 1.0
        finally:
            s.shutdown()

    def test_concurrent_threads_coalesce_into_one_batch(self):
        """Acceptance: N threads submitting single verifies concurrently
        produce ≥1 multi-request device batch (occupancy > 1)."""
        s = DeviceScheduler(use_device_default=False)
        try:
            rows = make_rows(8, tamper={3})
            s.pause()
            results: dict = {}

            def submit(i):
                results[i] = s.submit_rows([rows[i]]).result(timeout=30)

            threads = [
                threading.Thread(target=submit, args=(i,)) for i in range(8)
            ]
            for t in threads:
                t.start()
            # let every thread enqueue before the (paused) loop assembles
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and sum(
                len(q) for q in s._queues.values()
            ) < 8:
                time.sleep(0.005)
            s.resume()
            for t in threads:
                t.join(timeout=30)
            assert sorted(results) == list(range(8))
            for i, rr in results.items():
                assert rr.mask.tolist() == [i != 3]
            seqs = [rr.batch_seq for rr in results.values()]
            occupancy = max(seqs.count(q) for q in set(seqs))
            assert occupancy > 1  # one device batch served many requests
        finally:
            s.shutdown()

    def test_verdicts_match_direct_path(self, notary, alice):
        from corda_tpu.verifier import check_transactions

        s = DeviceScheduler(use_device_default=False)
        try:
            good = issue_tx(notary, alice, 1)
            victim = issue_tx(notary, alice, 2)
            sig = victim.sigs[0]
            forged = dataclasses.replace(victim, sigs=(dataclasses.replace(
                sig, signature=b"\0" * len(sig.signature)
            ),))
            stripped = dataclasses.replace(
                move_tx(notary, alice, good), sigs=(
                    move_tx(notary, alice, good).sigs[0],
                ),
            )
            stxs = [good, forged, stripped]
            allowed = [set(), set(), set()]
            direct = check_transactions(stxs, allowed, use_device=False)
            routed = s.submit_transactions(
                stxs, allowed, use_device=False
            ).result(timeout=30)
            assert [type(r) for r in routed.results] == [
                type(r) for r in direct.results
            ]
            assert routed.n_sigs == direct.n_sigs
        finally:
            s.shutdown()

    def test_class_fairness_interactive_rides_first_batch(self):
        """A bulk backlog cannot starve interactive work: the reserved
        interactive share puts a late-arriving interactive singleton into
        the FIRST batch, ahead of queued bulk rows."""
        s = DeviceScheduler(
            use_device_default=False,
            max_batch_rows=8, min_batch_rows=8,
        )
        try:
            s.pause()
            bulk1 = s.submit_rows(make_rows(6), priority=BULK)
            bulk2 = s.submit_rows(make_rows(6), priority=BULK)
            inter = s.submit_rows(make_rows(1), priority=INTERACTIVE)
            s.resume()
            r_b1 = bulk1.result(timeout=30)
            r_b2 = bulk2.result(timeout=30)
            r_i = inter.result(timeout=30)
            assert r_i.batch_seq == r_b1.batch_seq  # rode the first batch
            assert r_b2.batch_seq > r_b1.batch_seq  # second bulk waited
            assert r_i.mask.all() and r_b1.mask.all() and r_b2.mask.all()
        finally:
            s.shutdown()

    def test_over_deadline_work_is_shed(self):
        s = DeviceScheduler(use_device_default=False)
        try:
            shed0 = node_metrics().counter("serving.shed").count
            s.pause()
            doomed = s.submit_rows(make_rows(2), deadline_s=0.01)
            live = s.submit_rows(make_rows(1))
            time.sleep(0.05)
            s.resume()
            with pytest.raises(DeadlineExceededError):
                doomed.result(timeout=30)
            assert live.result(timeout=30).mask.tolist() == [True]
            assert node_metrics().counter("serving.shed").count == shed0 + 1
        finally:
            s.shutdown()

    def test_bounded_queue_rejects_at_admission(self):
        s = DeviceScheduler(
            use_device_default=False,
            max_queue_rows=4,
        )
        try:
            s.pause()
            ok = s.submit_rows(make_rows(4))
            with pytest.raises(SchedulerSaturatedError):
                s.submit_rows(make_rows(1))
            s.resume()
            assert ok.result(timeout=30).mask.all()
        finally:
            s.shutdown()

    def test_adaptive_cap_splits_a_deep_queue(self):
        """Many queued rows split into several capped batches instead of
        one giant dispatch (the pipeline-depth shape)."""
        s = DeviceScheduler(
            use_device_default=False,
            max_batch_rows=8, min_batch_rows=8,
        )
        try:
            s.pause()
            futs = [s.submit_rows(make_rows(4)) for _ in range(6)]  # 24 rows
            s.resume()
            seqs = {f.result(timeout=30).batch_seq for f in futs}
            assert len(seqs) >= 3  # 24 rows over a cap of 8 → ≥3 batches
        finally:
            s.shutdown()


class TestSchedulerLifecycle:
    def test_shutdown_completes_queued_and_inflight(self):
        s = DeviceScheduler(use_device_default=False)
        s.pause()
        futs = [s.submit_rows(make_rows(1)) for _ in range(4)]
        # shutdown overrides pause and DRAINS: every queued future resolves
        s.shutdown()
        for f in futs:
            assert f.result(timeout=5).mask.tolist() == [True]
        assert not s._dispatcher.is_alive()
        assert not s._collector.is_alive()

    def test_double_shutdown_is_noop(self):
        s = DeviceScheduler(use_device_default=False)
        s.shutdown()
        s.shutdown()  # second call returns immediately, no error
        with pytest.raises(SchedulerClosedError):
            s.submit_rows(make_rows(1))

    def test_global_scheduler_replaced_after_shutdown(self):
        from corda_tpu.serving import shutdown_scheduler

        a = device_scheduler()
        shutdown_scheduler()
        b = device_scheduler()
        try:
            assert a is not b and a.closed and not b.closed
        finally:
            pass  # leave the (healthy) global for later tests


class TestCompletionOrderSettle:
    def test_ready_batch_settles_before_older_inflight(self):
        """The collector harvests in-flight batches in COMPLETION order:
        a later batch whose device work already landed resolves its
        futures before an older batch still computing, and the
        out-of-order settle is counted (serving.settle_reorder)."""
        import numpy as np

        from corda_tpu.serving.scheduler import _InFlight, _Request

        s = DeviceScheduler(use_device_default=False, depth=3)
        settle_order: list = []
        gate = threading.Event()

        def fake_entry(tag, seq, ready=False, block_on=None):
            class FakePending:
                device_mask = np.ones(1, dtype=bool)

                def ready(self):
                    return ready

                def collect(self):
                    if block_on is not None:
                        assert block_on.wait(timeout=10)
                    settle_order.append(tag)
                    return np.ones(1, dtype=bool)

            req = _Request(
                [object()], Future(), SERVICE, False, None,
                time.monotonic(), None,
            )
            return _InFlight(
                [req], FakePending(), 1, [(0, 0)], seq, time.monotonic()
            )

        reorders = node_metrics().counter("serving.settle_reorder")
        before = reorders.count
        # oldest: a gate batch that blocks its collect until released, so
        # the two probe batches are both in the collector's live set
        entries = [
            fake_entry("gate", 101, block_on=gate),
            fake_entry("old-unready", 102, ready=False),
            fake_entry("new-ready", 103, ready=True),
        ]
        try:
            with s._lock:
                s._inflight += len(entries)
            for e in entries:
                s._inflight_q.put(e)
            gate.set()
            for e in entries:
                rr = e.requests[0].future.result(timeout=10)
                assert rr.mask.tolist() == [True]
            # the ready batch settled before the older un-ready one
            assert settle_order.index("new-ready") < settle_order.index(
                "old-unready"
            )
            assert reorders.count > before
        finally:
            s.shutdown()

    def test_host_batches_skip_device_slot_wait(self):
        """A host-only batch must never queue behind the device depth
        bound: with the pipeline saturated by a slow device batch, a
        host-routed request still dispatches and settles immediately."""
        import numpy as np

        from corda_tpu.serving.scheduler import _InFlight, _Request

        s = DeviceScheduler(use_device_default=False, depth=1)
        gate = threading.Event()

        class StuckPending:
            device_mask = np.ones(1, dtype=bool)

            def ready(self):
                return False

            def collect(self):
                assert gate.wait(timeout=30)
                return np.ones(1, dtype=bool)

        stuck = _InFlight(
            [_Request([object()], Future(), SERVICE, False, None,
                      time.monotonic(), None)],
            StuckPending(), 1, [(0, 0)], 900, time.monotonic(),
        )
        try:
            with s._lock:
                s._inflight += 1  # device pipeline saturated (depth=1)
            s._inflight_q.put(stuck)
            t0 = time.monotonic()
            rr = s.submit_rows(make_rows(1)).result(timeout=5)
            assert rr.mask.tolist() == [True]
            assert time.monotonic() - t0 < 5, "host batch waited on device"
            # a DEVICE-routed request whose deadline expires while its
            # batch is parked at the slot wait is shed there, not
            # dispatched late with a verdict nobody waits for
            late = s.submit_rows(
                make_rows(1), use_device=True, deadline_s=0.05,
            )
            with pytest.raises(DeadlineExceededError):
                late.result(timeout=10)
        finally:
            gate.set()
            stuck.requests[0].future.result(timeout=10)
            s.shutdown()


# ------------------------------------------------- verifier service tier

class TestVerifierServiceRouting:
    def test_single_verify_ignores_window(self, notary, alice):
        """The scheduler-routed service dispatches a lone request
        immediately even with a pathological window_s configured — the
        window is a legacy-path knob, not a latency tax."""
        svc = BatchedVerifierService(window_s=5.0, use_device=False)
        try:
            t0 = time.monotonic()
            fut = svc.verify_signed(issue_tx(notary, alice))
            assert fut.result(timeout=10) is None
            assert time.monotonic() - t0 < 2.0
            assert svc.stats["txs"] == 1
        finally:
            svc.shutdown()

    def test_cross_client_coalescing_through_hub_and_service(self, notary,
                                                             alice):
        """Three DIFFERENT client kinds — verifier service futures, flow
        hot-path checks (ServiceHub helper), and a notary window — land
        in one device batch while the scheduler is held."""
        from corda_tpu.node import ServiceHub
        from corda_tpu.notary import (
            BatchedNotaryService,
            PersistentUniquenessProvider,
        )

        sched = device_scheduler()
        occupancy = node_metrics().timer("serving.batch_occupancy")
        svc = BatchedVerifierService(use_device=False)
        hub = ServiceHub(verifier_service=svc)
        nkp = generate_keypair()
        nparty = Party(CordaX500Name("CoalesceNotary", "Oslo", "NO"),
                       nkp.public)
        notary_svc = BatchedNotaryService(
            nparty, nkp, PersistentUniquenessProvider(),
            use_device=False, validating=False,
        )
        stx = issue_tx(notary, alice)
        sched.pause()
        try:
            futs = [
                svc.verify_signed(issue_tx(notary, alice, v), None, set())
                for v in (11, 12)
            ]
            hub_done: list = []

            def via_hub():
                hub.verify_stx_signatures(stx, set())
                hub_done.append(True)

            threads = [threading.Thread(target=via_hub) for _ in range(2)]
            for t in threads:
                t.start()
            pending = notary_svc.dispatch_batch(
                [(issue_tx(notary, alice, 13), None, "coalesce")],
                pending_ids=None, pipelined=True,
            )
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and sum(
                len(q) for q in sched._queues.values()
            ) < 5:
                time.sleep(0.005)
        finally:
            sched.resume()
        for f in futs:
            assert f.result(timeout=30) is None
        for t in threads:
            t.join(timeout=30)
        assert hub_done == [True, True]
        report = pending.collect()
        assert report.ok
        # the batch that served them recorded multi-request occupancy
        assert occupancy.count > 0
        assert occupancy._last >= 5 or occupancy._max >= 5
        notary_svc.shutdown()
        svc.shutdown()

    def test_shutdown_completes_inflight_futures(self, notary, alice):
        svc = BatchedVerifierService(use_device=False)
        futs = [
            svc.verify_signed(issue_tx(notary, alice, v)) for v in range(6)
        ]
        svc.shutdown()
        for f in futs:
            assert f.done()
            assert f.result(timeout=1) is None
        # double shutdown is a no-op; new submits are refused
        svc.shutdown()
        with pytest.raises(VerificationError):
            svc.verify_signed(issue_tx(notary, alice))

    def test_legacy_shutdown_drains_queue_no_hung_flusher(self, notary,
                                                          alice):
        svc = BatchedVerifierService(
            use_device=False, use_scheduler=False, window_s=0.5
        )
        futs = [
            svc.verify_signed(issue_tx(notary, alice, v)) for v in range(4)
        ]
        svc.shutdown()  # drains the queue without waiting the window out
        for f in futs:
            assert f.result(timeout=1) is None
        assert not svc._flusher.is_alive()
        svc.shutdown()  # no-op
        with pytest.raises(VerificationError):
            svc.verify_signed(issue_tx(notary, alice))

    def test_legacy_leftover_window_ages_from_first_arrival(self, notary,
                                                            alice):
        """The aging fix: items sliced off beyond max_batch must NOT
        restart the window — with max_batch=1 and window_s=0.3, four
        queued requests flush in ~one window, not four."""
        svc = BatchedVerifierService(
            use_device=False, use_scheduler=False,
            max_batch=1, window_s=0.3,
        )
        try:
            t0 = time.monotonic()
            futs = [
                svc.verify_signed(issue_tx(notary, alice, v))
                for v in (21, 22, 23, 24)
            ]
            for f in futs:
                assert f.result(timeout=10) is None
            elapsed = time.monotonic() - t0
            # buggy leftover handling waited a fresh window per item
            # (≥ 0.9 s); the aged window clears all four in ~0.3 s
            assert elapsed < 0.8, f"leftover window restarted: {elapsed:.2f}s"
            assert svc.stats["batches"] == 4
        finally:
            svc.shutdown()


# ----------------------------------------------------- fault-plan failover

class TestServingFaultPlan:
    def test_injected_dispatch_failure_fails_over_to_host(self):
        """A seeded FaultPlan failing the serving.dispatch site forces the
        whole batch onto the host reference path: identical verdicts, a
        counted failover, a recorded trace event."""
        s = DeviceScheduler(use_device_default=True)
        failover0 = node_metrics().counter("serving.device_failover").count
        inj = install_injector(FaultInjector(FaultPlan(
            seed=77, fail_sites=(("serving.dispatch", 1),),
        )))
        try:
            rows = make_rows(4, tamper={2})
            rr = s.submit_rows(rows, use_device=True).result(timeout=30)
        finally:
            clear_injector()
            s.shutdown()
        assert rr.mask.tolist() == [True, True, False, True]
        assert rr.n_device == 0  # nothing settled on device
        assert node_metrics().counter(
            "serving.device_failover"
        ).count == failover0 + 1
        assert any(
            e.kind == "op-fail" and e.site == "serving.dispatch"
            for e in inj.trace
        )

    def test_trader_demo_via_scheduler_under_faultplan(self, request):
        """Acceptance: the trader-demo path runs through the scheduler
        with results identical to the direct path, including under a
        seeded FaultPlan whose device-op failures fail every dispatch
        over to host."""
        from corda_tpu.finance import CashIssueFlow, CashState
        from corda_tpu.samples import trader_demo
        from corda_tpu.testing import MockNetworkNodes

        n = 3
        inj = install_injector(FaultInjector(FaultPlan(seed=9, op_fail_p=1.0)))
        request.addfinalizer(clear_injector)
        with MockNetworkNodes() as net:
            bank = net.create_node("Bank A")
            buyer = net.create_node("Bank B")
            notary = net.create_notary_node("Notary", validating=True)
            # device-batched verifier tier on every node: flow verifies
            # route INTERACTIVE through the process-global scheduler with
            # use_device=True, and the plan fails each device dispatch —
            # the host failover must keep results identical
            for node in (bank, buyer, notary):
                node.services.transaction_verifier_service = (
                    BatchedVerifierService(use_device=True)
                )
            papers = []
            for _ in range(n):
                buyer.run_flow(
                    CashIssueFlow(1500, "GBP", b"\x01", notary.party)
                )
                issued = trader_demo.issue_paper(bank, notary.party)
                papers.append(
                    bank.services.to_state_and_ref(StateRef(issued.id, 0))
                )
            handles = [
                bank.smm.start_flow(
                    trader_demo.SellerFlow(buyer.party, sar, 900, "GBP")
                )
                for sar in papers
            ]
            for h in handles:
                assert h.result.result(timeout=120) is not None
            seller_cash = sum(
                sr.state.data.amount.quantity
                for sr in bank.services.vault_service.unconsumed_states(
                    CashState
                )
            )
            assert seller_cash == 900 * n  # identical to the direct path
        assert any(e.site == "serving.dispatch" for e in inj.trace)

    def test_dag_via_scheduler_matches_direct_under_faultplan(self, notary,
                                                              alice):
        """Acceptance: the 1k-hop-DAG shape (scaled down) through the
        scheduler equals the direct path, with and without injected
        device-op failures."""
        from corda_tpu.parallel import verify_transaction_dag

        chain = [issue_tx(notary, alice, 64)]
        for _ in range(12):
            chain.append(move_tx(notary, alice, chain[-1]))
        dag = {s.id: s for s in chain}
        allowed = lambda s: {notary[0].owning_key}  # noqa: E731

        direct = verify_transaction_dag(
            dag, allowed_missing_fn=allowed, use_device=False,
            use_scheduler=False,
        )
        routed = verify_transaction_dag(
            dag, allowed_missing_fn=allowed, use_device=False,
        )
        assert routed.order == direct.order
        assert routed.n_sigs == direct.n_sigs
        assert routed.consumed == direct.consumed

        install_injector(FaultInjector(FaultPlan(seed=5, op_fail_p=1.0)))
        try:
            faulted = verify_transaction_dag(
                dag, allowed_missing_fn=allowed, use_device=True,
                recompute_ids=False,
            )
        finally:
            clear_injector()
        assert faulted.order == direct.order
        assert faulted.n_sigs == direct.n_sigs


# -------------------------------------------- self-healing serving plane

def _resilience_counters():
    names = (
        "serving.hedge.fired", "serving.hedge.won_host",
        "serving.hedge.won_device", "serving.hedge.discarded",
        "serving.quarantine.strikes", "serving.quarantine.entered",
        "serving.quarantine.readmitted", "serving.quarantine.probes",
        "serving.quarantine.probe_failures",
        "serving.quarantine.host_routed", "serving.breaker.opened",
        "serving.breaker.closed", "serving.breaker.host_routed",
        "serving.redispatch", "serving.device_failover",
        "serving.hedge.rerouted", "serving.hedge.won_sibling",
        "serving.mesh.striped", "serving.mesh.no_eligible",
        "serving.mesh.megabatch", "serving.mesh.megabatch_rows",
        "serving.mesh.megabatch_failover",
    )
    # read through the registry snapshot, NOT m.counter(name): a counter
    # lookup CREATES the metric, and names like serving.device_failover
    # must not exist until the production path really increments them
    # (test_observability pins exactly that sectioning contract)
    snap = node_metrics().snapshot()
    return {n: snap.get(n, {}).get("count", 0) for n in names}


def _delta(before):
    after = _resilience_counters()
    return {k: after[k] - before[k] for k in before}


class TestResilience:
    """ISSUE 9 acceptance: the self-healing serving plane — quarantine
    state machine, hedged dispatch, circuit breaker, deterministic
    re-dispatch — driven by injected stalls and crashes."""

    def _rows(self, n=5, tamper=(3,)):
        rows = make_rows(n, tamper=set(tamper))
        expected = [i not in tamper for i in range(n)]
        return rows, expected

    def test_quarantine_state_machine_fake_clock(self):
        """HEALTHY → SUSPECT → QUARANTINED → PROBATION → HEALTHY under a
        fake clock: strikes accumulate (a clean settle heals a suspect),
        K strikes evict, probes respect exponential backoff, a failed
        canary doubles it, a passing one readmits."""
        from corda_tpu.serving import (
            HEALTHY,
            PROBATION,
            QUARANTINED,
            SUSPECT,
            ResiliencePolicy,
        )

        before = _resilience_counters()
        now = [100.0]
        seen: list = []
        verdicts = [False, True]

        def probe_runner(ordinal):
            # the probe observes PROBATION: the canary is in flight
            seen.append((ordinal, pol.quarantine.state(ordinal)))
            return verdicts.pop(0)

        pol = ResiliencePolicy(
            strikes=2, probe_backoff_s=1.0, probe_backoff_max_s=8.0,
            probe_runner=probe_runner, clock=lambda: now[0],
            flight_dump_on_quarantine=False,
        )
        q = pol.quarantine
        assert q.state(3) == HEALTHY
        pol.on_hedge_fired(3)                  # stall evidence: strike 1
        assert q.state(3) == SUSPECT
        pol.on_settle_ok(3)                    # clean settle heals
        assert q.state(3) == HEALTHY
        pol.on_dispatch_failure(3)
        assert q.state(3) == SUSPECT
        assert pol.admit_device(3)             # suspects still serve
        pol.on_dispatch_failure(3)             # strike 2: evicted
        assert q.state(3) == QUARANTINED
        assert not pol.admit_device(3)
        pol.maybe_probe(sync=True)             # backoff not elapsed
        assert seen == [] and q.state(3) == QUARANTINED
        now[0] += 1.1
        pol.maybe_probe(sync=True)             # canary FAILS
        assert seen == [(3, PROBATION)]
        assert q.state(3) == QUARANTINED
        now[0] += 1.1                          # doubled backoff (2.0s)
        pol.maybe_probe(sync=True)             # ... not elapsed yet
        assert len(seen) == 1
        now[0] += 1.0
        pol.maybe_probe(sync=True)             # canary PASSES
        assert seen[-1] == (3, PROBATION)
        assert q.state(3) == HEALTHY
        assert pol.admit_device(3)
        d = _delta(before)
        # 1 hedge strike (healed) + 2 dispatch-failure strikes
        assert d["serving.quarantine.strikes"] == 3
        assert d["serving.quarantine.entered"] == 1
        assert d["serving.quarantine.probes"] == 2
        assert d["serving.quarantine.probe_failures"] == 1
        assert d["serving.quarantine.readmitted"] == 1

    def test_stall_and_crash_full_cycle(self):
        """The acceptance scenario end to end on real CPU device
        dispatches: one injected STALL is hedged to host (every request
        completed exactly once, verdicts identical to the host oracle,
        the loser's late readback discarded), one injected CRASH is
        re-dispatched while its strike quarantines the ordinal, a REAL
        known-answer canary probe readmits it, and every new counter
        reconciles with the scenario's dispatch/settle counts."""
        from corda_tpu.serving import HEALTHY, ResiliencePolicy, ShapeTable

        before = _resilience_counters()
        pol = ResiliencePolicy(
            strikes=2, hedge_min_s=0.15, hedge_max_s=0.5,
            probe_backoff_s=0.1, breaker_threshold=10,
            flight_dump_on_quarantine=False,
        )
        s = DeviceScheduler(
            use_device_default=True,
            shapes=ShapeTable({"buckets": [8, 16, 32],
                               "source": "test-resilience"}),
            resilience=pol,
        )
        rows, expected = self._rows()
        inj = install_injector(FaultInjector(FaultPlan(
            seed=7,
            stall_sites=(("serving.dispatch", 2, 2.0),),
            fail_sites=(("serving.dispatch", 3),),
        )))
        try:
            # dispatch 1: clean warmup — seeds the EWMA the hedge
            # deadline derives from (nothing hedges before it exists)
            rr = s.submit_rows(rows, use_device=True).result(timeout=300)
            assert rr.mask.tolist() == expected and rr.n_device == 5
            ordinal = rr.device
            # dispatch 2: stalled in flight → hedged; host-oracle
            # verdicts, completed well before the 2 s stall expires
            t0 = time.monotonic()
            rr2 = s.submit_rows(rows, use_device=True).result(timeout=60)
            assert rr2.mask.tolist() == expected
            assert rr2.n_device == 0          # the host leg won
            assert time.monotonic() - t0 < 1.8
            assert pol.quarantine.state(ordinal) != HEALTHY  # strike 1
            # dispatch 3: crashes → strike 2 quarantines the ordinal and
            # the batch re-enters the queue; its retry host-routes
            rr3 = s.submit_rows(rows, use_device=True).result(timeout=60)
            assert rr3.mask.tolist() == expected and rr3.n_device == 0
            clear_injector()
            # the REAL canary probe (known-answer batch, must settle on
            # device) readmits the ordinal...
            deadline = time.monotonic() + 120
            while (pol.quarantine.state(ordinal) != HEALTHY
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert pol.quarantine.state(ordinal) == HEALTHY, (
                pol.quarantine.snapshot()
            )
            # ... after which traffic runs on device again
            rr4 = s.submit_rows(rows, use_device=True).result(timeout=300)
            assert rr4.mask.tolist() == expected and rr4.n_device == 5
        finally:
            clear_injector()
            s.shutdown()
        d = _delta(before)
        # counters reconcile exactly with the scenario: 1 stall → 1
        # fired hedge, won by host, late readback discarded at drain; 1
        # crash → 1 re-dispatch (NOT a legacy failover), 1 quarantine
        # episode entered + readmitted via ≥1 probe
        assert d["serving.hedge.fired"] == 1, d
        assert d["serving.hedge.won_host"] == 1, d
        assert d["serving.hedge.won_device"] == 0, d
        assert d["serving.hedge.discarded"] == 1, d
        assert d["serving.quarantine.entered"] == 1, d
        assert d["serving.quarantine.readmitted"] == 1, d
        assert d["serving.quarantine.probes"] >= 1, d
        assert d["serving.quarantine.host_routed"] >= 1, d
        assert d["serving.redispatch"] == 1, d
        assert d["serving.device_failover"] == 0, d
        # hedge algebra: every fired hedge resolved exactly one winner
        assert d["serving.hedge.won_host"] + d["serving.hedge.won_device"] \
            == d["serving.hedge.fired"]

    def test_breaker_trips_open_routes_host_and_recloses(self):
        """K consecutive device failures trip the breaker; while open,
        every batch host-routes with ZERO device enqueues (the fault
        site is never consulted again); a half-open canary closes it
        and traffic returns to the device."""
        from corda_tpu.serving import (
            BREAKER_CLOSED,
            BREAKER_OPEN,
            ResiliencePolicy,
            ShapeTable,
        )

        before = _resilience_counters()
        pol = ResiliencePolicy(
            strikes=50,                      # isolate the breaker
            breaker_threshold=2, breaker_backoff_s=0.3,
            redispatch_limit=1, probe_runner=lambda o: True,
            flight_dump_on_quarantine=False,
        )
        s = DeviceScheduler(
            use_device_default=True,
            shapes=ShapeTable({"buckets": [8, 16],
                               "source": "test-breaker"}),
            resilience=pol,
        )
        rows, expected = self._rows(3, tamper=())
        inj = install_injector(FaultInjector(FaultPlan(seed=3,
                                                       op_fail_p=1.0)))
        try:
            # dispatch fails, re-dispatch fails again → 2 consecutive
            # failures → OPEN; the exhausted request host-fails-over
            rr = s.submit_rows(rows, use_device=True).result(timeout=60)
            assert rr.mask.tolist() == expected and rr.n_device == 0
            assert pol.breaker.state == BREAKER_OPEN
            site_calls = sum(
                1 for e in inj.trace if e.site == "serving.dispatch"
            )
            assert site_calls == 2
            # while open: host-routed, zero device enqueues
            rr2 = s.submit_rows(rows, use_device=True).result(timeout=60)
            assert rr2.mask.tolist() == expected and rr2.n_device == 0
            assert sum(
                1 for e in inj.trace if e.site == "serving.dispatch"
            ) == site_calls
            clear_injector()
            # half-open canary (stubbed) closes it after the backoff
            deadline = time.monotonic() + 30
            while (pol.breaker.state != BREAKER_CLOSED
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert pol.breaker.state == BREAKER_CLOSED
            rr3 = s.submit_rows(rows, use_device=True).result(timeout=300)
            assert rr3.mask.tolist() == expected and rr3.n_device == 3
        finally:
            clear_injector()
            s.shutdown()
        d = _delta(before)
        assert d["serving.breaker.opened"] == 1, d
        assert d["serving.breaker.closed"] == 1, d
        assert d["serving.breaker.host_routed"] >= 1, d
        assert d["serving.redispatch"] == 1, d
        assert d["serving.device_failover"] == 1, d  # budget exhausted

    def test_watchdog_eviction_dumps_flight_record_once(self, tmp_path,
                                                        monkeypatch):
        """ISSUE 9 satellite: a watchdog device.unhealthy event strikes
        the ordinal through the devicemon subscription hook, and the
        quarantine entry writes EXACTLY ONE flight dump per episode —
        carrying the breaker/quarantine status, parseable via the
        existing read_flight_dump path."""
        from corda_tpu.observability.devicemon import (
            DeviceMonitor,
            DeviceWatchdog,
        )
        from corda_tpu.observability.slo import read_flight_dump
        from corda_tpu.serving import QUARANTINED, ResiliencePolicy
        from corda_tpu.serving import resilience as resilience_mod

        monkeypatch.setenv("CORDA_TPU_FLIGHT_DIR", str(tmp_path))
        now = [50.0]
        mon = DeviceMonitor(n_devices=1, enabled=True,
                            clock=lambda: now[0])
        pol = ResiliencePolicy(
            strikes=1, probe_backoff_s=60.0,
            probe_runner=lambda o: True, clock=lambda: now[0],
        )
        mon.subscribe(pol.on_device_event)
        resilience_mod.register_policy(pol)
        try:
            wd = DeviceWatchdog(mon, stall_s=2.0)
            mon.record_dispatch(0, rows=4)   # in flight, then silence
            now[0] += 5.0
            events = wd.check_once()
            assert any(e["kind"] == "device.unhealthy" for e in events)
            assert pol.quarantine.state(0) == QUARANTINED
            dumps = sorted(tmp_path.glob("corda_tpu_flight_*.jsonl"))
            assert len(dumps) == 1, dumps
            parsed = read_flight_dump(str(dumps[0]))
            assert parsed["header"]["reason"] == "device-quarantine:0"
            res = parsed["resilience"]
            assert res["enabled"] is True
            assert res["quarantine"]["ordinals"]["0"]["state"] \
                == QUARANTINED
            assert res["breaker"]["state_name"] == "closed"
            # more strikes in the SAME episode: no second dump
            pol.on_dispatch_failure(0)
            wd.check_once()                  # edge-triggered: no re-flag
            assert len(
                sorted(tmp_path.glob("corda_tpu_flight_*.jsonl"))
            ) == 1
        finally:
            mon.unsubscribe(pol.on_device_event)
            resilience_mod.unregister_policy(pol)

    def test_resilience_off_by_default(self):
        """No policy → no hedge thread, no policy registration, and the
        monitoring snapshot's resilience section is a bare disabled
        marker (the devicemon/slo overhead contract, extended)."""
        from corda_tpu.serving import active_policy

        s = DeviceScheduler(use_device_default=False)
        try:
            assert s._resilience is None and s._hedge is None
            assert active_policy() is None
            rr = s.submit_rows(make_rows(2)).result(timeout=30)
            assert rr.mask.all()
            assert monitoring_snapshot()["resilience"] == {
                "enabled": False
            }
        finally:
            s.shutdown()


# --------------------------------------------------------- mesh scheduling

def _install_fake_dispatch(monkeypatch, calls, release=None,
                           stall_first=False):
    """Replace the real device dispatch with a shape-faithful fake:
    records the PINNED ordinal of every dispatch in ``calls`` (the
    ``device=`` placement the mesh scheduler resolved), returns a
    pending that is ready when ``release`` is set (always ready with no
    gate). ``stall_first`` makes ONLY the first dispatch stall on the
    gate — the hedge tests' shape — while every later dispatch (the
    sibling leg) settles instantly. Placement/chaos tests run on fakes
    deliberately: pinning a warm shape to a NEW ordinal is a multi-
    second XLA compile per chip, and these tests assert scheduling, not
    kernels (the mega-batch parity test below runs the real thing)."""
    import numpy as np

    class FakePending:
        def __init__(self, n, bucket, gate):
            self.device_rows = n
            self.device_mask = np.ones(n, dtype=bool)
            self.padded_lanes = bucket
            self._n = n
            self._gate = gate

        def ready(self):
            return self._gate is None or self._gate.is_set()

        def collect(self):
            if self._gate is not None:
                assert self._gate.wait(timeout=30)
            return np.ones(self._n, dtype=bool)

    def fake(rows, *, use_device=True, min_bucket=None, device=None):
        first = not calls
        calls.append(None if device is None else int(device.id))
        gate = release
        if stall_first and not first:
            gate = None
        return FakePending(len(rows), min_bucket or len(rows), gate)

    monkeypatch.setattr(
        "corda_tpu.verifier.batch.dispatch_signature_rows", fake
    )


class TestMeshScheduling:
    """PR 13 acceptance: the mesh-sharded scheduler — stripe placement
    over all 8 XLA CPU devices, bounded depth spread, sibling-chip
    hedging, quarantine-driven rerouting, and whole-stripe mega-batch
    fusion with the consumed-set all-gather."""

    def _shapes(self, buckets):
        from corda_tpu.serving import ShapeTable

        return ShapeTable({"buckets": buckets, "source": "test-mesh"})

    def test_saturated_stripe_covers_mesh_with_bounded_spread(
        self, monkeypatch
    ):
        """Acceptance pin: a saturated scheduler stripes across ≥7
        distinct ordinals and the per-ordinal in-flight depth spread
        never exceeds 2; every placement reservation drains at settle."""
        calls: list = []
        release = threading.Event()
        _install_fake_dispatch(monkeypatch, calls, release=release)
        s = DeviceScheduler(
            use_device_default=True, depth=8, mesh=True,
            megabatch_fill=9.9,  # never fuse: this test pins placement
            shapes=self._shapes([4]),
        )
        try:
            futs = [
                s.submit_rows(make_rows(4), use_device=True)
                for _ in range(12)
            ]
            # let the dispatcher saturate its depth before releasing
            deadline = time.monotonic() + 10
            while len(calls) < 8 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert len(calls) >= 8, calls
            release.set()
            for f in futs:
                assert f.result(timeout=30).mask.tolist() == [True] * 4
            assert len(set(calls)) >= 7, calls
            assert s._mesh_spread_max <= 2
            with s._lock:
                dispatches = dict(s._ord_dispatches)
                inflight = dict(s._ord_inflight)
            assert sum(dispatches.values()) == 12
            assert len(dispatches) >= 7
            # every depth reservation was released exactly once
            assert all(v == 0 for v in inflight.values()), inflight
        finally:
            release.set()
            s.shutdown()

    def test_quarantined_ordinal_reroutes_to_siblings(self, monkeypatch):
        """Seeded chaos: with ordinal 3 quarantined before the storm,
        its share of the buckets lands on sibling chips — zero dispatches
        to the evicted ordinal, zero lost or double-completed futures."""
        from corda_tpu.serving import QUARANTINED, ResiliencePolicy

        calls: list = []
        _install_fake_dispatch(monkeypatch, calls)
        pol = ResiliencePolicy(
            strikes=2, probe_runner=lambda o: False,
            flight_dump_on_quarantine=False,
        )
        pol.on_dispatch_failure(3)
        pol.on_dispatch_failure(3)
        assert pol.quarantine.state(3) == QUARANTINED
        s = DeviceScheduler(
            use_device_default=True, depth=4, mesh=True,
            megabatch_fill=9.9, resilience=pol, shapes=self._shapes([4]),
        )
        try:
            futs = [
                s.submit_rows(make_rows(4), use_device=True)
                for _ in range(16)
            ]
            results = [f.result(timeout=30) for f in futs]
        finally:
            s.shutdown()
        # zero lost futures (every one resolved above) and correct,
        # single verdicts for each
        assert len(results) == 16
        assert all(r.mask.tolist() == [True] * 4 for r in results)
        assert 3 not in calls, calls
        assert 3 not in s._ord_dispatches
        # the surviving 7 chips absorbed the evicted ordinal's share
        assert len(set(calls)) == 7, calls

    def test_fired_hedge_reroutes_to_sibling_chip_first(self, monkeypatch):
        """A stalled in-flight batch is re-run on a SIBLING chip before
        the host leg: first result wins, the sibling's verdicts complete
        the futures, and the hedge loss strikes the ORIGINAL ordinal."""
        from corda_tpu.serving import SUSPECT, ResiliencePolicy

        before = _resilience_counters()
        calls: list = []
        release = threading.Event()
        _install_fake_dispatch(monkeypatch, calls, release=release,
                               stall_first=True)
        pol = ResiliencePolicy(
            strikes=10, breaker_threshold=10,
            hedge_min_s=0.05, hedge_max_s=0.2,
            probe_runner=lambda o: False,
            flight_dump_on_quarantine=False,
        )
        s = DeviceScheduler(
            use_device_default=True, depth=2, mesh=True,
            megabatch_fill=9.9, resilience=pol, shapes=self._shapes([8]),
        )
        rows = make_rows(8)
        scheme = getattr(rows[0][0], "scheme_id", None)
        try:
            # pre-warm the shape on EVERY ordinal and seed the EWMA the
            # hedge deadline derives from (per-ordinal warm keys would
            # otherwise rightly refuse to hedge a first-dispatch compile)
            with s._lock:
                s._warm_keys |= {(scheme, 8, o) for o in range(8)}
                s._latency_ewma = 0.01
            rr = s.submit_rows(rows, use_device=True).result(timeout=30)
            assert rr.mask.tolist() == [True] * 8
            assert len(calls) == 2 and calls[1] != calls[0], calls
            assert rr.device == calls[1]      # the sibling completed it
            # the stall's evidence landed on the ORIGINAL ordinal
            assert pol.quarantine.state(calls[0]) == SUSPECT
            # the loser's late readback is discarded at settle
            release.set()
            deadline = time.monotonic() + 10
            while (_delta(before)["serving.hedge.discarded"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.02)
        finally:
            release.set()
            s.shutdown()
        d = _delta(before)
        assert d["serving.hedge.fired"] == 1, d
        assert d["serving.hedge.rerouted"] == 1, d
        assert d["serving.hedge.won_sibling"] == 1, d
        assert d["serving.hedge.won_host"] == 0, d
        assert d["serving.hedge.discarded"] == 1, d

    def test_megabatch_parity_and_consumed_set(self, monkeypatch):
        """Acceptance: a full ed25519 bucket fuses into ONE whole-stripe
        shard_map dispatch whose verdicts are bit-identical to the
        per-ordinal path and the host oracle, with the notary
        consumed-set delta all-gathered alongside (one sha256 row per
        message, parity-checked against the host recomputation)."""
        import numpy as np

        from corda_tpu.serving.scheduler import _consumed_rows
        from corda_tpu.verifier.batch import dispatch_signature_rows

        # RLC would eat a FULL ed25519 bucket on host before any device
        # dispatch — this test must exercise the real mesh kernels
        monkeypatch.setenv("CORDA_TPU_BATCH_RLC", "0")
        before = _resilience_counters()
        s = DeviceScheduler(
            use_device_default=True, mesh=True, megabatch_fill=0.0,
            shapes=self._shapes([64]),
        )
        rows = make_rows(64, tamper={5})
        expected = [i != 5 for i in range(64)]
        try:
            rr = s.submit_rows(rows, use_device=True).result(timeout=600)
            assert rr.mask.tolist() == expected
            assert rr.n_device == 64          # settled ON the mesh
            # the same window through the per-ordinal path: bit-identical
            single = dispatch_signature_rows(
                rows, use_device=True, min_bucket=64
            ).collect()
            assert single[:64].tolist() == rr.mask.tolist()
            # consumed-set all-gather parity vs the host recomputation
            p = s._dispatch_mega(rows, 64)
            assert p.collect()[:64].tolist() == expected
            spent = np.asarray(p.spent_all)
            host_rows = _consumed_rows([m for _k, _s, m in rows])
            assert (spent[:64] == host_rows).all()
        finally:
            s.shutdown()
        d = _delta(before)
        assert d["serving.mesh.megabatch"] >= 1, d
        assert d["serving.mesh.megabatch_rows"] >= 64, d
        assert d["serving.mesh.megabatch_failover"] == 0, d

    def test_empty_stripe_routes_host(self, monkeypatch):
        """Every ordinal down → whole-mesh host routing: verdicts from
        the host reference path, serving.mesh.no_eligible counted, and
        the per-ordinal breakers' collective state reads OPEN."""
        from corda_tpu.serving import (
            BREAKER_OPEN,
            ResiliencePolicy,
        )

        calls: list = []
        _install_fake_dispatch(monkeypatch, calls)
        before = _resilience_counters()
        pol = ResiliencePolicy(
            strikes=100, breaker_threshold=1,
            probe_runner=lambda o: False,
            flight_dump_on_quarantine=False,
        )
        for o in range(8):
            pol.breaker_for(o).record_failure()
        assert pol.breaker_state_mesh() == BREAKER_OPEN
        s = DeviceScheduler(
            use_device_default=True, mesh=True, resilience=pol,
            shapes=self._shapes([4]),
        )
        try:
            rr = s.submit_rows(
                make_rows(4, tamper={2}), use_device=True
            ).result(timeout=30)
            assert rr.mask.tolist() == [True, True, False, True]
            assert rr.n_device == 0           # host reference path
        finally:
            s.shutdown()
        assert calls == []                    # zero device enqueues
        assert _delta(before)["serving.mesh.no_eligible"] >= 1


# ------------------------------------------------ monitoring + RPC surface

class TestServingObservability:
    def test_monitoring_snapshot_has_serving_section(self):
        s = DeviceScheduler(use_device_default=False)  # registers gauges
        try:
            s.submit_rows(make_rows(2)).result(timeout=30)
            snap = monitoring_snapshot()
            assert "serving" in snap and "process" in snap
            assert "queue_depth" in snap["serving"]
            assert "batches" in snap["serving"]
            assert snap["serving"]["rows"]["count"] >= 2
            assert not any(
                k.startswith("serving.") for k in snap["process"]
            )
        finally:
            s.shutdown()

    def test_rpc_op_and_read_binding(self, notary, alice):
        from corda_tpu.node import ServiceHub
        from corda_tpu.rpc.bindings import (
            monitoring_snapshot_value,
            serving_metrics_value,
        )
        from corda_tpu.rpc.ops import CordaRPCOps

        hub = ServiceHub(
            verifier_service=BatchedVerifierService(use_device=False)
        )
        ops = CordaRPCOps(hub, smm=None)
        live = serving_metrics_value(ops)
        before = live.get().get("requests", {}).get("count", 0)
        hub.verify_stx_signatures(issue_tx(notary, alice), set())
        after = live.refresh().get("requests", {}).get("count", 0)
        assert after >= before + 1
        full = monitoring_snapshot_value(ops).get()
        assert set(full) >= {"serving", "process", "node"}
        hub.transaction_verifier_service.shutdown()

    def test_hub_helper_rejects_bad_signature(self, notary, alice):
        from corda_tpu.node import ServiceHub

        hub = ServiceHub(
            verifier_service=BatchedVerifierService(use_device=False)
        )
        stx = issue_tx(notary, alice)
        sig = stx.sigs[0]
        forged = dataclasses.replace(stx, sigs=(dataclasses.replace(
            sig, signature=b"\0" * len(sig.signature)
        ),))
        with pytest.raises(InvalidSignatureError):
            hub.verify_stx_signatures(forged, set())
        hub.verify_stx_signatures(stx, set())  # the good one passes
        hub.transaction_verifier_service.shutdown()


# --------------------------------------------------------- bench --smoke

class TestBenchSmoke:
    def test_bench_smoke_exercises_scheduler(self, tmp_path):
        """tier-1 guard: `bench.py --smoke` (the fast scheduler path) must
        pass on CPU so scheduler regressions fail tests, not just the TPU
        bench — and its JSON must round-trip through the perf gate."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"), "--smoke"],
            capture_output=True, text=True, timeout=300, env=env,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        line = proc.stdout.strip().splitlines()[-1]
        out = json.loads(line)
        assert out["ok"] is True
        assert out["max_batch_occupancy"] > 1
        assert out["notary_txs"] == 24
        # acceptance: the per-stage profile section carries a
        # compile/execute split and batch-efficiency ratios for at least
        # the ed25519 and txid paths (docs/OBSERVABILITY.md §Profiling)
        for kernel in ("ed25519.verify", "txid"):
            prof = out["profile"][kernel]
            assert prof["compile_count"] >= 1
            assert prof["execute_count"] >= 1
            assert 0 < prof["batch_efficiency"] <= 1.0
        # acceptance (ISSUE 7): the devicemon pass emits a per-ordinal
        # devices table whose rows/dispatches reconciled in-process
        # against the scheduler's counters (deviceless CPU backend = a
        # 1-device mesh); --check-schema below validates its shape
        assert out["devicemon_rows"] == 10
        assert out["devicemon_dispatches"] == 2
        assert sum(
            e["rows"] for e in out["devices"].values()
        ) == out["devicemon_rows"]
        for entry in out["devices"].values():
            assert entry["inflight"] == 0
            assert entry["rows"] <= entry["padded_rows"]
        # acceptance (ISSUE 9): the resilience pass injected one stall
        # (hedged, host won, late readback discarded) and one crash
        # (re-dispatched; quarantine entered AND exited via a real
        # canary probe) — the schema mode below validates the section
        res = out["resilience"]
        assert res["hedge_fired"] == 1
        assert res["hedge_won_host"] == 1
        assert res["quarantine_entered"] == 1
        assert res["quarantine_readmitted"] == 1
        assert res["redispatched"] == 1
        assert res["breaker_state"] == 0
        # acceptance (ISSUE 13): the mesh pass striped every visible
        # ordinal exactly once (conftest exports an 8-virtual-device
        # XLA_FLAGS, so the bench subprocess sees a real stripe), fused
        # a full bucket into one shard_map mega-batch, and proved both
        # the verdict and consumed-set all-gather parities
        mc = out["multichip"]
        assert mc["ordinals_hit"] == mc["n_devices"]
        assert mc["scaling_efficiency"] >= 0.8
        assert mc["allgather_parity_ok"] == 1
        assert mc["mega_parity_ok"] == 1
        if mc["n_devices"] > 1:
            assert mc["n_devices"] == 8
            assert mc["megabatch_rows"] == 64

        # acceptance: a baseline generated from this same output gates
        # green; an injected profile regression gates red — and the
        # schema mode accepts the devices table
        result = tmp_path / "smoke.json"
        result.write_text(line)
        baseline = tmp_path / "PERF_BASELINE.json"
        gate = os.path.join(repo, "tools_perf_gate.py")

        def run_gate(*args):
            return subprocess.run(
                [sys.executable, gate, *args],
                capture_output=True, text=True, timeout=60,
            )

        schema = run_gate("--result", str(result), "--check-schema")
        assert schema.returncode == 0, schema.stdout + schema.stderr
        wrote = run_gate("--result", str(result), "--write-baseline",
                         "--baseline", str(baseline))
        assert wrote.returncode == 0, wrote.stdout + wrote.stderr
        ok = run_gate("--result", str(result), "--baseline", str(baseline))
        assert ok.returncode == 0, ok.stdout + ok.stderr
        doctored = dict(out)
        doctored["profile"] = json.loads(json.dumps(out["profile"]))
        doctored["profile"]["ed25519.verify"]["rows_per_sec"] *= 0.4
        bad_path = tmp_path / "smoke_bad.json"
        bad_path.write_text(json.dumps(doctored))
        bad = run_gate("--result", str(bad_path), "--baseline", str(baseline))
        assert bad.returncode == 1, bad.stdout + bad.stderr
        assert "ed25519.verify" in bad.stdout
