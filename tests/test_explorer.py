"""Explorer + DemoBench tier tests — the observability GUIs re-targeted
at browser/terminal (reference: tools/explorer Main.kt, tools/demobench
DemoBench.kt). The explorer's page and every JSON feed serve real node
data; DemoBench manages a live subprocess ensemble."""

import json
import time
import urllib.request

import pytest

from corda_tpu.rpc import CordaRPCOps
from corda_tpu.testing import MockNetworkNodes
from corda_tpu.tools.explorer import ExplorerServer


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return r.read()


class TestExplorer:
    def test_page_and_feeds_serve_node_data(self):
        from corda_tpu.finance import CashIssueFlow

        with MockNetworkNodes() as net:
            node = net.create_node("Bank A")
            notary = net.create_notary_node("Notary", validating=True)
            node.run_flow(CashIssueFlow(500, "GBP", b"\x01", notary.party))
            ops = CordaRPCOps(node.services, node.smm)
            server = ExplorerServer(ops).start()
            try:
                page = _get(server.port, "/").decode()
                assert "corda_tpu explorer" in page and "/api/vault" in page
                status = json.loads(_get(server.port, "/api/status"))
                assert "Bank A" in status["identity"]
                peers = json.loads(_get(server.port, "/api/peers"))
                assert len(peers) == 2
                notaries = json.loads(_get(server.port, "/api/notaries"))
                assert any("Notary" in n for n in notaries)
                vault = json.loads(_get(server.port, "/api/vault"))
                assert vault["total"] == 1
                assert "500" in json.dumps(vault["states"])
                flows = json.loads(_get(server.port, "/api/registered-flows"))
                assert isinstance(flows, list)  # mocknet registers none
                machines = json.loads(_get(server.port, "/api/flows"))
                assert machines == []  # nothing in flight
                bad = json.loads(_get(server.port, "/api/nope"))
                assert "error" in bad
            finally:
                server.stop()


@pytest.mark.slow
class TestDemoBench:
    def test_ensemble_lifecycle_shell_and_explorer(self, tmp_path):
        from corda_tpu.tools.demobench import DemoBench

        with DemoBench(base_dir=str(tmp_path)) as bench:
            bench.add_notary()
            alice = bench.add_node("O=Alice,L=London,C=GB")
            assert all(h.alive for h in bench.nodes)
            # shell attaches over RPC
            import io

            out = io.StringIO()
            shell = bench.shell(alice, out=out)
            shell.run_command("run ping")
            assert "pong" in out.getvalue()
            # explorer serves the spawned node's data
            server = bench.explorer(alice)
            deadline = time.monotonic() + 20
            status = None
            while time.monotonic() < deadline:
                try:
                    status = json.loads(_get(server.port, "/api/status"))
                    break
                except Exception:
                    time.sleep(0.3)
            assert status and "Alice" in status["identity"]
        # context exit tears the processes down
        assert all(not h.alive for h in bench.nodes)
