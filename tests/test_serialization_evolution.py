"""Serialization version-skew matrix — the role of the reference's
EvolutionSerializer (node-api/.../serialization/amqp/EvolutionSerializer.kt)
plus its rename transforms: a rolling upgrade runs old and new versions of
a type on either end of every wire (node ↔ verifier ↔ RPC client), in BOTH
directions, and neither side may wedge.

Writer/reader skew is simulated the way it happens on a real fabric: the
"other version" of a type is expressed as raw wire bytes (a GenericRecord
encodes under any type name with any field set — exactly what an
old/new peer's encoder emits), decoded against the locally registered
class.
"""

import dataclasses

import pytest

from corda_tpu.serialization import (
    GenericRecord,
    SerializationError,
    cbe_serializable,
    deserialize,
    register_rename,
    serialize,
)
from corda_tpu.serialization.cbe import _ENCODERS, _REGISTRY


@pytest.fixture(autouse=True)
def scoped_registry():
    """Every test's registrations are rolled back (the registry is global
    process state — leaking a test type would poison later decodes)."""
    saved_r = dict(_REGISTRY)
    saved_e = dict(_ENCODERS)
    yield
    _REGISTRY.clear()
    _REGISTRY.update(saved_r)
    _ENCODERS.clear()
    _ENCODERS.update(saved_e)


def wire_bytes(type_name: str, **fields) -> bytes:
    """Bytes exactly as a peer running a different version would emit them:
    an object tagged ``type_name`` carrying ``fields``."""
    return serialize(GenericRecord(type_name, tuple(fields.items())))


class TestAddedField:
    def test_old_writer_new_reader_defaults(self):
        @cbe_serializable(name="evo.Trade")
        @dataclasses.dataclass(frozen=True)
        class TradeV2:
            amount: int
            currency: str = "GBP"     # added in v2, with default

        got = deserialize(wire_bytes("evo.Trade", amount=5))  # v1 payload
        assert got == TradeV2(5, "GBP")

    def test_added_field_without_default_fails_cleanly(self):
        @cbe_serializable(name="evo.Strict")
        @dataclasses.dataclass(frozen=True)
        class StrictV2:
            amount: int
            currency: str             # added WITHOUT default: upgrade bug

        with pytest.raises(SerializationError, match="evolution mismatch"):
            deserialize(wire_bytes("evo.Strict", amount=5))


class TestRemovedField:
    def test_old_writer_new_reader_drops_removed(self):
        @cbe_serializable(name="evo.Slim")
        @dataclasses.dataclass(frozen=True)
        class SlimV2:
            amount: int               # v1 also had `legacy_note`

        got = deserialize(
            wire_bytes("evo.Slim", amount=9, legacy_note="old writers send this")
        )
        assert got == SlimV2(9)

    def test_new_writer_old_reader_takes_default(self):
        # the old reader's class still carries the field the new writer
        # removed; it must fall back to its default
        @cbe_serializable(name="evo.OldReader")
        @dataclasses.dataclass(frozen=True)
        class V1:
            amount: int
            legacy_note: str = ""

        got = deserialize(wire_bytes("evo.OldReader", amount=3))
        assert got == V1(3, "")


class TestRenamedField:
    def test_alias_maps_old_key(self):
        @cbe_serializable(name="evo.Renamed",
                          field_aliases={"amount": "qty"})
        @dataclasses.dataclass(frozen=True)
        class RenamedV2:
            amount: int

        assert deserialize(wire_bytes("evo.Renamed", qty=7)) == RenamedV2(7)
        # new writers use the new key; alias must not shadow it
        assert deserialize(
            wire_bytes("evo.Renamed", amount=8)
        ) == RenamedV2(8)


class TestRenamedType:
    def test_old_type_name_decodes_to_current_class(self):
        @cbe_serializable(name="evo.NewName",
                          renamed_from=("evo.OldName",))
        @dataclasses.dataclass(frozen=True)
        class Renamed:
            x: int

        got = deserialize(wire_bytes("evo.OldName", x=4))
        assert got == Renamed(4)
        # encoding always carries the CURRENT name
        assert b"evo.NewName" in serialize(Renamed(4))
        assert b"evo.OldName" not in serialize(Renamed(4))

    def test_alias_collision_rejected(self):
        @cbe_serializable(name="evo.A")
        @dataclasses.dataclass(frozen=True)
        class A:
            x: int = 0

        @cbe_serializable(name="evo.B")
        @dataclasses.dataclass(frozen=True)
        class B:
            x: int = 0

        with pytest.raises(SerializationError, match="refusing to alias"):
            register_rename("evo.A", B)


class TestWireSkewAcrossTiers:
    def test_skewed_verification_request_degrades_to_error_reply(self):
        """node ↔ verifier: a worker on the OLD version receiving a
        request it cannot construct (a field lost its default upstream, or
        the payload predates a required field) must answer a structured
        error — the node future completes exceptionally, never hangs
        (pairs with the dead-letter/deadline machinery; reference
        contract: VerifierApi.kt:40-58)."""
        from corda_tpu.messaging import DurableQueueBroker
        from corda_tpu.verifier.worker import (
            VERIFICATION_REQUESTS_QUEUE,
            OutOfProcessVerifierService,
            VerificationFailedError,
            VerifierWorker,
        )

        broker = DurableQueueBroker()
        service = OutOfProcessVerifierService(
            broker, "skew-node", request_timeout_s=30
        )
        worker = VerifierWorker(broker).start()
        try:
            from concurrent.futures import Future
            import time as _t

            from corda_tpu.verifier.worker import _PendingRequest

            fut = Future()
            nonce = 424242
            with service._lock:
                service._pending[nonce] = _PendingRequest(
                    fut, b"", _t.monotonic() + 30
                )
            # a VerificationRequest missing the required stx/ltx/reply_to
            # fields — the add-without-default skew shape on the wire
            broker.publish(
                VERIFICATION_REQUESTS_QUEUE,
                wire_bytes("verifier.Request", nonce=nonce),
                msg_id=f"vreq-verifier.responses.skew-node-{nonce}",
            )
            with pytest.raises(VerificationFailedError,
                               match="malformed request"):
                fut.result(timeout=10)
        finally:
            worker.stop()
            service.shutdown()
            broker.close()

    def test_contract_only_request_none_vs_legacy_zero_sentinel(self):
        """node ↔ verifier: contract-only requests carry ``stx=None``
        (CBE's native null form) since r5; pre-r5 writers punned the
        absent field as the int ``0``. A current worker must treat BOTH
        wire shapes as "no signed form — skip signature checking" (the
        skew test the r4 review asked for when retiring the pun)."""
        from corda_tpu.verifier.worker import VerificationRequest

        class _LtxStub:
            notary = None

            def verify(self):
                self.verified = True

        from corda_tpu.verifier.worker import VerifierWorker

        worker = VerifierWorker.__new__(VerifierWorker)
        worker._use_device = False
        for legacy_stx in (None, 0):
            raw = serialize(VerificationRequest(9, legacy_stx, None, "q"))
            req = deserialize(raw)
            assert req.stx == legacy_stx
            ltx = _LtxStub()
            req = VerificationRequest(req.nonce, req.stx, ltx, req.reply_to)
            assert worker._verify(req) == ""
            assert ltx.verified

    def test_newer_rpc_client_against_old_server(self):
        """RPC client ↔ node: a client one version ahead sends a request
        carrying a field this server's RpcRequest doesn't know; the server
        must serve it, not drop the session."""
        from corda_tpu.rpc.server import RpcRequest

        got = deserialize(wire_bytes(
            "rpc.Request",
            request_id="r1", username="u", password="p", method="ping",
            args=(), kwargs_blob=b"", reply_to="client",
            priority_hint=3,          # v-next field this server predates
        ))
        assert isinstance(got, RpcRequest)
        assert got.method == "ping"

    def test_carpenter_narrowing_after_widening(self):
        """carpenter tier: once widened by a new-version record, an
        old-version (narrower) record still decodes through the synthesized
        class with defaults — both skew directions on an unknown type."""
        from corda_tpu.serialization import carpent

        wide = carpent(deserialize(
            wire_bytes("evo.Foreign", a=1, b=2)
        ))
        narrow = carpent(deserialize(wire_bytes("evo.Foreign", a=5)))
        assert type(narrow) is type(wide)
        assert narrow.a == 5 and narrow.b is None
