"""Causal-profiler tests — the COZ virtual-speedup machinery: the
experiment's delay arithmetic (k−1 dilation of every delayable
non-target booking, per-event cap), the session-scoped flowprof phase
listener, the k-rescale cell math and the speedup ledger's ranking, the
record/section/Prometheus surfaces, and one real planted-bottleneck
validation (±25%, the acceptance bound the bench smoke and the perf
gate pin)."""

import sys

import pytest

import corda_tpu.observability.flowprof  # noqa: F401 — module, not the
# package's re-exported flowprof() accessor, which shadows it in
# `import ... as` resolution
flowprof_mod = sys.modules["corda_tpu.observability.flowprof"]

from corda_tpu.observability.causal import (  # noqa: E402
    DELAY_CAP_S,
    DELAYABLE_PHASES,
    CausalProfiler,
    SyntheticPipeline,
    _Experiment,
    build_ledger,
    causal_section,
    configure_causal,
    last_result,
    prometheus_lines,
    record_result,
    run_synthetic,
    validate_planted,
)
from corda_tpu.observability.exposition import parse_prometheus
from corda_tpu.observability.flowprof import PHASES, FlowProfiler


class FakeSleep:
    """Capture the inserted virtual delays instead of sleeping."""

    def __init__(self):
        self.calls = []

    def __call__(self, seconds):
        self.calls.append(seconds)

    @property
    def total(self):
        return sum(self.calls)


# ----------------------------------------------------------- experiment

class TestExperiment:
    def test_mult_is_k_minus_one(self):
        # x=0.5 → k=2 → every other phase dilated by 1.0× its booking
        assert _Experiment("host_verify", 0.5).mult == pytest.approx(1.0)
        # x=0.75 → k=4 → dilation 3×
        assert _Experiment("host_verify", 0.75).mult == pytest.approx(3.0)
        assert _Experiment("host_verify", 0.0).mult == 0.0

    def test_rejects_out_of_range_speedup(self):
        with pytest.raises(ValueError):
            _Experiment("host_verify", 1.0)
        with pytest.raises(ValueError):
            _Experiment("host_verify", -0.1)


class TestOnPhase:
    def _profiler(self):
        sleep = FakeSleep()
        return CausalProfiler(sleep=sleep), sleep

    def test_dilates_delayable_non_target(self):
        prof, sleep = self._profiler()
        with prof.session(), prof.experiment("host_verify", 0.5) as exp:
            prof._on_phase("serialize", 0.010)
            prof._on_phase("checkpoint", 0.004)
        assert sleep.calls == pytest.approx([0.010, 0.004])
        assert exp.delays == 2
        assert exp.inserted_s == pytest.approx(0.014)

    def test_skips_target_waits_and_off_worker_phases(self):
        prof, sleep = self._profiler()
        with prof.session(), prof.experiment("host_verify", 0.5):
            prof._on_phase("host_verify", 0.010)    # the target itself
            prof._on_phase("queue_wait", 0.010)     # demand-driven wait
            prof._on_phase("lock_wait", 0.010)
            prof._on_phase("message_transit", 0.010)  # off-worker
            prof._on_phase("notary_rtt", 0.010)
            prof._on_phase("engine_other", 0.010)   # close residual
            prof._on_phase("serialize", 0.0)        # zero booking
            prof._on_phase("serialize", -1.0)
        assert sleep.calls == []

    def test_caps_pathological_bookings(self):
        prof, sleep = self._profiler()
        with prof.session(), prof.experiment("host_verify", 0.5) as exp:
            prof._on_phase("serialize", 10.0)
        assert sleep.calls == [DELAY_CAP_S]
        assert exp.inserted_s == pytest.approx(DELAY_CAP_S)

    def test_noop_outside_an_experiment(self):
        prof, sleep = self._profiler()
        with prof.session():
            prof._on_phase("serialize", 0.010)
        assert sleep.calls == []

    def test_delayable_phases_are_real_worker_phases(self):
        assert set(DELAYABLE_PHASES) <= set(PHASES)
        for never in ("queue_wait", "lock_wait", "message_transit",
                      "notary_rtt", "engine_other"):
            assert never not in DELAYABLE_PHASES


class TestSessionListener:
    def test_session_installs_and_clears_the_flowprof_listener(self):
        prof = CausalProfiler(sleep=FakeSleep())
        assert flowprof_mod._phase_listener is None
        with prof.session():
            assert flowprof_mod._phase_listener is not None
        assert flowprof_mod._phase_listener is None

    def test_real_flowprof_bookings_reach_the_experiment(self):
        """Frame exit on a live account fires the listener with the
        booked seconds — the integration the whole profiler rides."""
        clock = [0.0]

        def fake_clock():
            return clock[0]

        fp = FlowProfiler(clock=fake_clock)
        sleep = FakeSleep()
        prof = CausalProfiler(sleep=sleep)
        with prof.session(), prof.experiment("host_verify", 0.5) as exp:
            acct = fp.open("f1", "PaymentFlow")
            with fp.activate(acct):
                with fp.frame("serialize"):
                    clock[0] += 0.010
                with fp.frame("host_verify"):   # target: never dilated
                    clock[0] += 0.020
            fp.close("f1")
        assert sleep.total == pytest.approx(0.010)
        assert exp.delays == 1


# ------------------------------------------------------- cells & ledger

class TestRunAndLedger:
    def test_run_rescales_cells_against_baseline(self):
        prof = CausalProfiler(sleep=FakeSleep())
        qps = iter([100.0, 80.0, 60.0])
        result = prof.run(lambda: next(qps),
                          phases=("host_verify",), speedups=(0.25, 0.5))
        assert result["schema"] == 1
        assert result["baseline_qps"] == 100.0
        c25, c50 = result["cells"]
        # k-rescale: predicted = qps / (1 - x)
        assert c25["predicted_qps"] == pytest.approx(80.0 / 0.75)
        assert c50["predicted_qps"] == pytest.approx(120.0)
        assert c50["predicted_gain_qps"] == pytest.approx(20.0)
        assert c50["predicted_gain_pct"] == pytest.approx(20.0)
        # the ledger keeps host_verify's best cell
        (row,) = result["ledger"]
        assert row["phase"] == "host_verify"
        assert row["speedup_pct"] == 50.0

    def test_run_rejects_unknown_phase(self):
        prof = CausalProfiler(sleep=FakeSleep())
        with pytest.raises(ValueError):
            prof.run(lambda: 1.0, phases=("warp_drive",))

    def test_build_ledger_best_cell_per_phase_desc(self):
        cells = [
            {"phase": "a", "speedup_pct": 25.0, "predicted_qps": 5.0,
             "predicted_gain_qps": 1.0, "predicted_gain_pct": 25.0},
            {"phase": "a", "speedup_pct": 50.0, "predicted_qps": 9.0,
             "predicted_gain_qps": 5.0, "predicted_gain_pct": 125.0},
            {"phase": "b", "speedup_pct": 50.0, "predicted_qps": 7.0,
             "predicted_gain_qps": 3.0, "predicted_gain_pct": 75.0},
        ]
        ledger = build_ledger(cells)
        assert [(r["phase"], r["speedup_pct"]) for r in ledger] == \
            [("a", 50.0), ("b", 50.0)]
        gains = [r["predicted_gain_qps"] for r in ledger]
        assert gains == sorted(gains, reverse=True)


# ----------------------------------------------------- process surfaces

class TestSurfaces:
    def test_section_disabled_until_a_run_records(self):
        configure_causal(reset=True)
        assert causal_section() == {"enabled": False}
        assert last_result() is None
        assert prometheus_lines() == []

    def test_record_result_round_trips_the_section(self):
        configure_causal(reset=True)
        try:
            out = record_result({
                "schema": 1, "baseline_qps": 10.0, "cells": [],
                "ledger": [
                    {"phase": "host_verify", "speedup_pct": 50.0,
                     "predicted_qps": 12.0, "predicted_gain_qps": 2.0,
                     "predicted_gain_pct": 20.0},
                ],
            })
            assert out["enabled"]
            assert causal_section() is last_result()
            assert causal_section()["baseline_qps"] == 10.0
            text = "\n".join(prometheus_lines()) + "\n"
            samples = parse_prometheus(text)
            key = ('cordatpu_causal_predicted_gain_qps'
                   '{phase="host_verify",speedup_pct="50"}')
            assert key in samples
        finally:
            configure_causal(reset=True)


# ------------------------------------------- planted-bottleneck (real)

class TestPlantedBottleneck:
    def test_synthetic_pipeline_books_real_phases(self):
        clockless = FlowProfiler()
        pipe = SyntheticPipeline(
            (("serialize", 0.001), ("host_verify", 0.001)),
            workers=2, items_per_worker=3, prof=clockless,
        )
        qps = pipe.probe()
        assert qps > 0
        snap = clockless.snapshot()
        cls = snap["classes"]["SyntheticItem"]
        assert cls["flows"] == 6
        assert cls["phases"]["serialize"] > 0
        assert cls["phases"]["host_verify"] > 0

    def test_run_synthetic_validates_within_tolerance(self):
        """The acceptance bound: predict the clean pipeline's capacity
        from experiments on the planted one, ±25% on the gain — one
        real (sleeping) run, small quotas to stay CI-cheap."""
        configure_causal(reset=True)
        try:
            result = run_synthetic(
                phases=("host_verify",), speedups=(0.5,),
                workers=3, items_per_worker=12,
            )
            assert result["source"] == "synthetic"
            assert result["enabled"]
            val = result["validation"]
            assert val["ok"], val
            assert val["rel_err"] <= val["tol"] == 0.25
            assert result["baseline_qps"] > 0
            for cell in result["cells"]:
                assert cell["experiment_qps"] > 0
                assert cell["inserted_delays"] > 0
            assert result["ledger"]
            # recorded as the process's last causal run
            assert causal_section()["enabled"]
            assert causal_section()["source"] == "synthetic"
        finally:
            configure_causal(reset=True)

    def test_validate_planted_rejects_unknown_phase(self):
        with pytest.raises(ValueError):
            validate_planted(phase="queue_wait")
