"""Device-resident sharded state store (PR 17, docs/STATE_STORE.md).

Acceptance axes:

- table ops on the 8-virtual-device mesh: insert / probe / remove /
  tombstone / probe-window overflow and the occupancy accounting;
- the randomized double-spend sweep: ``DeviceShardedUniquenessProvider``
  verdicts AND ``consumed_digest()`` bit-identical to the
  ``InMemoryUniquenessProvider`` host-map oracle across fresh commits,
  double-spends, idempotent client retries, multi-ref requests,
  intra-batch duplicate keys (host-routed) and empty-ref requests;
- the spill tier: probe-window overflow spills host-side with exact
  membership, and a ``statestore.spill`` fault is a HARD error
  (``StateStoreSpillError``), never silent;
- ``statestore.probe`` faults: provider fails over to the host shadow
  with identical verdicts (scale mode without a shadow raises), the
  vault index degrades to its SQL answer;
- durable recovery: restart-from-directory rebuilds the device table
  (digest parity, device probes hit), and the kill-storm harness drives
  the durable statestore through every PR 10 crash site + a torn WAL
  tail, asserting the rebuilt ``consumed_digest()`` matches a
  never-crashed host oracle bit-for-bit;
- vault index wiring: record/consume maintains the device index beside
  the SQL pages, coin selection cross-checks, owner-bucket counts;
- the serving mega-batch fusion: the registered membership screen
  counts device-resident hits and ``collect()`` harvests the counters;
- satellites: single-pass ``InMemoryUniquenessProvider.commit_batch``
  under ONE lock acquisition with loop-identical verdicts, and the
  seed-deterministic streamed ledger generators (bounded memory, flagged
  double-spends, a slow-marked 10^7-state scale run);
- off-by-default: a fresh subprocess without ``CORDA_TPU_STATESTORE``
  never imports jax from the statestore package, allocates no tables,
  registers no ``statestore.*`` metrics and reports
  ``{"enabled": False}``.
"""

import dataclasses
import hashlib
import itertools
import json
import os
import random
import subprocess
import sys

import numpy as np
import pytest

from corda_tpu.crypto import SecureHash, generate_keypair
from corda_tpu.durability import DurableStore
from corda_tpu.faultinject import (
    FaultInjector,
    FaultPlan,
    InjectedCrash,
    clear as clear_injector,
    install as install_injector,
    truncate_wal_tail,
)
from corda_tpu.ledger import (
    Amount,
    CordaX500Name,
    Party,
    StateRef,
    TransactionBuilder,
    register_contract,
)
from corda_tpu.node import NodeVaultService
from corda_tpu.node.monitoring import node_metrics
from corda_tpu.notary import InMemoryUniquenessProvider, NotaryError
from corda_tpu.serialization import register_custom
from corda_tpu.statestore import (
    DeviceShardedTable,
    DeviceShardedUniquenessProvider,
    DeviceVaultIndex,
    StateStoreSpillError,
    active_mega_screen,
    key_rows,
    payload_rows,
    statestore_section,
)
from corda_tpu.testing.generated_ledger import (
    GeneratedLedger,
    stream_commit_requests,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tx(i: int) -> SecureHash:
    return SecureHash(hashlib.sha256(b"ss-tx-%d" % i).digest())


def _ref(i: int, idx: int = 0) -> StateRef:
    return StateRef(
        SecureHash(hashlib.sha256(b"ss-ref-%d" % i).digest()), idx
    )


def _counters() -> dict:
    return {
        k: v["count"] for k, v in node_metrics().snapshot().items()
        if k.startswith("statestore.") and v.get("type") == "counter"
    }


def _delta(before: dict) -> dict:
    after = _counters()
    return {
        k: after.get(k, 0) - before.get(k, 0)
        for k in set(after) | set(before)
        if after.get(k, 0) != before.get(k, 0)
    }


@pytest.fixture(autouse=True)
def _no_leaked_injector():
    clear_injector()
    yield
    clear_injector()


def _assert_verdicts_equal(want, got):
    assert len(want) == len(got)
    for w, g in zip(want, got):
        assert (w is None) == (g is None), (w, g)
        if w is not None:
            assert w.state_history == g.state_history


# ----------------------------------------------------------- table ops

class TestDeviceTable:
    def test_insert_probe_remove_tombstone(self):
        t = DeviceShardedTable(slots_per_shard=64, max_probe=8, name="t1")
        keys = [b"k-%d" % i for i in range(16)]
        rows = key_rows(keys)
        payloads = payload_rows([hashlib.sha256(k).digest() for k in keys])
        overflow = t.insert_rows(rows, payloads)
        assert not overflow.any()
        assert t.n_live == 16
        absent = key_rows([b"absent-%d" % i for i in range(8)])
        assert t.probe_rows(rows).all()
        assert not t.probe_rows(absent).any()
        # re-offering present rows is idempotent (no duplicate rows)
        overflow = t.insert_rows(rows, payloads)
        assert not overflow.any()
        assert t.n_live == 16
        # tombstone half; membership flips only for the removed half
        removed = t.remove_rows(rows[:8])
        assert removed.all()
        assert t.n_live == 8
        bits = t.probe_rows(rows)
        assert not bits[:8].any() and bits[8:].all()
        # removing an absent key reports False, removes nothing
        assert not t.remove_rows(absent).any()
        # a tombstoned slot is reusable
        assert not t.insert_rows(rows[:4], payloads[:4]).any()
        assert t.probe_rows(rows[:4]).all()
        assert t.n_live == 12
        stats = t.stats()
        assert stats["live_rows"] == 12
        assert stats["shards"] >= 1
        assert 0 < stats["occupancy"] < 1

    def test_probe_window_overflow_reported(self):
        t = DeviceShardedTable(slots_per_shard=8, max_probe=2, name="t2")
        keys = [b"ovf-%d" % i for i in range(48)]
        rows = key_rows(keys)
        payloads = payload_rows(
            [hashlib.sha256(k).digest() for k in keys]
        )
        overflow = t.insert_rows(rows, payloads)
        # 48 rows into windows of 2 over 8-slot shards MUST overflow some
        assert overflow.any() and not overflow.all()
        bits = t.probe_rows(rows)
        assert (bits == ~overflow).all()
        assert t.n_live == int((~overflow).sum())

    def test_count_tag(self):
        t = DeviceShardedTable(slots_per_shard=64, max_probe=8, name="t3")
        keys = [b"tag-%d" % i for i in range(12)]
        tags = np.array([0x11] * 5 + [0x33] * 7, np.int32)
        t.insert_rows(
            key_rows(keys),
            payload_rows([hashlib.sha256(k).digest() for k in keys]),
            tags,
        )
        assert t.count_tag(0x11) == 5
        assert t.count_tag(0x33) == 7
        assert t.count_tag(0x55) == 0


# -------------------------------------------- randomized oracle parity

class TestOracleParity:
    def test_randomized_double_spend_sweep(self):
        """Verdicts AND consumed_digest() bit-identical to the host-map
        oracle over 10 randomized batches mixing fresh commits,
        double-spends, idempotent retries, multi-ref requests,
        intra-batch duplicate keys and empty-ref requests."""
        rng = random.Random(1707)
        oracle = InMemoryUniquenessProvider()
        dev = DeviceShardedUniquenessProvider(
            slots_per_shard=256, max_probe=16
        )
        before = _counters()
        counter = itertools.count()

        def fresh_refs(k):
            return [_ref(next(counter)) for _ in range(k)]

        committed = []
        try:
            for batch_no in range(10):
                reqs = []
                for _ in range(11):
                    roll = rng.random()
                    if roll < 0.15 and committed:
                        reqs.append(rng.choice(committed))   # retry
                    elif roll < 0.35 and committed:
                        states = rng.choice(committed)[0]
                        reqs.append((
                            [rng.choice(states)],
                            _tx(10000 + next(counter)), "mallory",
                        ))
                    else:
                        reqs.append((
                            fresh_refs(rng.randint(1, 3)),
                            _tx(20000 + next(counter)), "party",
                        ))
                # intra-batch duplicate keys: first-wins, host-routed
                shared = fresh_refs(1)[0]
                reqs.append(([shared] + fresh_refs(1),
                             _tx(31000 + batch_no), "dup-a"))
                reqs.append(([shared], _tx(32000 + batch_no), "dup-b"))
                reqs.append(([], _tx(33000 + batch_no), "empty"))
                want = oracle.commit_batch(reqs)
                got = dev.commit_batch(reqs)
                _assert_verdicts_equal(want, got)
                for req, w in zip(reqs, want):
                    if w is None and req[0] and req not in committed:
                        committed.append(req)
            assert dev.consumed_digest() == oracle.consumed_digest()
            assert dev.device_divergence() == 0
            d = _delta(before)
            assert d.get("statestore.ab_mismatch", 0) == 0
            assert d.get("statestore.host_routed", 0) >= 20
            assert d.get("statestore.conflicts", 0) >= 10
        finally:
            dev.close()

    def test_same_batch_fresh_commit_and_identical_retry(self):
        """An identical retry of a fresh commit in the SAME batch (dup
        keys, both idempotently succeed) installs the key ONCE on
        device — no duplicate rows, digest parity held."""
        oracle = InMemoryUniquenessProvider()
        dev = DeviceShardedUniquenessProvider(
            slots_per_shard=64, max_probe=8
        )
        try:
            req = ([_ref(90001)], _tx(90001), "retry-client")
            reqs = [req, req]
            _assert_verdicts_equal(
                oracle.commit_batch(reqs), dev.commit_batch(reqs)
            )
            assert dev._table.n_live + dev.spill_count() == 1
            assert dev.consumed_digest() == oracle.consumed_digest()
        finally:
            dev.close()


# ------------------------------------------------------------ spill tier

class TestSpillTier:
    def test_overflow_spills_with_exact_membership(self):
        oracle = InMemoryUniquenessProvider()
        dev = DeviceShardedUniquenessProvider(
            slots_per_shard=8, max_probe=2
        )
        before = _counters()
        try:
            reqs = [
                ([_ref(40000 + i)], _tx(40000 + i), "loader")
                for i in range(48)
            ]
            for lo in range(0, 48, 8):
                _assert_verdicts_equal(
                    oracle.commit_batch(reqs[lo:lo + 8]),
                    dev.commit_batch(reqs[lo:lo + 8]),
                )
            assert dev.spill_count() > 0
            assert _delta(before).get("statestore.spills", 0) \
                == dev.spill_count()
            # double-spending SPILLED refs must still conflict exactly
            spilled_keys = set(dev._spill)
            thieves = [
                ([states[0]], _tx(41000 + i), "mallory")
                for i, (states, _t, _c) in enumerate(reqs)
                if states[0].txhash.bytes
                + states[0].index.to_bytes(4, "big") in spilled_keys
            ][:4]
            assert thieves, "no request landed in the spill tier"
            _assert_verdicts_equal(
                oracle.commit_batch(thieves), dev.commit_batch(thieves)
            )
            assert dev.consumed_digest() == oracle.consumed_digest()
            assert dev.device_divergence() == 0
            stats = dev.table_stats()
            assert stats["spill_rows"] == dev.spill_count()
        finally:
            dev.close()

    def test_spill_fault_is_a_hard_error(self):
        dev = DeviceShardedUniquenessProvider(
            slots_per_shard=8, max_probe=2
        )
        before = _counters()
        install_injector(FaultInjector(FaultPlan(
            seed=3, fail_sites=(("statestore.spill", 1),),
        )))
        try:
            with pytest.raises(StateStoreSpillError):
                for lo in range(0, 64, 8):
                    dev.commit_batch([
                        ([_ref(42000 + lo + i)], _tx(42000 + lo + i), "x")
                        for i in range(8)
                    ])
            assert _delta(before).get("statestore.spill_errors", 0) == 1
        finally:
            clear_injector()
            dev.close()


# ---------------------------------------------------- probe-fault paths

class TestProbeFaultFailover:
    def test_failover_to_shadow_keeps_verdict_parity(self):
        oracle = InMemoryUniquenessProvider()
        dev = DeviceShardedUniquenessProvider(
            slots_per_shard=256, max_probe=16
        )
        before = _counters()
        try:
            batch1 = [
                ([_ref(50000 + i)], _tx(50000 + i), "p") for i in range(6)
            ]
            install_injector(FaultInjector(FaultPlan(
                seed=4, fail_sites=(("statestore.probe", 1),),
            )))
            _assert_verdicts_equal(
                oracle.commit_batch(batch1), dev.commit_batch(batch1)
            )
            clear_injector()
            d = _delta(before)
            assert d.get("statestore.probe_failover", 0) == 1
            # failed-over commits live in the spill tier, so membership
            # (and a later double-spend verdict) stays exact on the
            # recovered device path
            assert dev.spill_count() == 6
            batch2 = (
                [([_ref(50000 + i)], _tx(51000 + i), "mallory")
                 for i in range(3)]
                + [([_ref(52000 + i)], _tx(52000 + i), "p")
                   for i in range(3)]
            )
            _assert_verdicts_equal(
                oracle.commit_batch(batch2), dev.commit_batch(batch2)
            )
            assert dev.consumed_digest() == oracle.consumed_digest()
            assert dev.device_divergence() == 0
        finally:
            clear_injector()
            dev.close()

    def test_scale_mode_probe_fault_raises(self):
        dev = DeviceShardedUniquenessProvider(
            slots_per_shard=64, max_probe=8, shadow=False
        )
        install_injector(FaultInjector(FaultPlan(
            seed=5, fail_sites=(("statestore.probe", 1),),
        )))
        try:
            with pytest.raises(NotaryError):
                dev.commit_batch([([_ref(53000)], _tx(53000), "p")])
        finally:
            clear_injector()
            dev.close()

    def test_durable_store_requires_shadow(self, tmp_path):
        with pytest.raises(ValueError):
            DeviceShardedUniquenessProvider(
                DurableStore(str(tmp_path), name="x"), shadow=False
            )


# ------------------------------------------------- durable recovery tier

# the kill-storm workload (mirrors tests/test_durability._workload):
# deliberate double-spends and client retries interleaved so every
# crash schedule crosses them
def _workload():
    ops = []
    for i in range(30):
        ops.append(("commit", [_ref(60000 + i)], _tx(60000 + i), True))
        if i == 9:
            ops.append(
                ("commit", [_ref(60003)], _tx(60900), False)
            )  # double spend
        if i == 14:
            ops.append(("snapshot",))
        if i == 15:
            ops.append(
                ("commit", [_ref(60010)], _tx(60010), True)
            )  # client retry
        if i == 24:
            ops.append(("snapshot",))
        if i == 25:
            ops.append(
                ("commit", [_ref(60020)], _tx(60901), False)
            )  # double spend
    return ops


def _drive_device(base_dir, schedule=(), torn_cut=0, seed=2026):
    """Run the workload against a durable DeviceShardedUniquenessProvider
    under a crash schedule; on InjectedCrash EVERY in-memory object —
    including the device table — is dropped (that is the crash), the
    torn-write injector optionally chops the unacked WAL tail, and a
    fresh provider rebuilds device state from the directory alone."""

    def build():
        return DeviceShardedUniquenessProvider(
            DurableStore(
                base_dir, name="ss", segment_max_bytes=256,
                snapshot_every=1 << 30,
            ),
            slots_per_shard=64, max_probe=8,
        )

    inj = None
    if schedule:
        inj = install_injector(FaultInjector(FaultPlan(
            seed=seed, crash_sites=tuple(schedule),
        )))
    prov = build()
    outcomes = []
    crashes = 0
    i = 0
    ops = _workload()
    while i < len(ops):
        op = ops[i]
        try:
            if op[0] == "snapshot":
                prov.snapshot_now()
                outcomes.append("snap")
            else:
                conflict = prov.commit_batch([(op[1], op[2], "ks")])[0]
                outcomes.append(conflict is None)
            i += 1  # ACKED: the client saw this op complete
        except InjectedCrash:
            crashes += 1
            prov = None
            if torn_cut:
                truncate_wal_tail(os.path.join(base_dir, "wal"), torn_cut)
            prov = build()
            # client retry of the same op — its ack never arrived
    if inj is not None:
        clear_injector()
    return outcomes, prov.consumed_digest(), crashes, prov


def _drive_host_oracle():
    """The never-crashed host-map oracle run of the same workload."""
    prov = InMemoryUniquenessProvider()
    outcomes = []
    for op in _workload():
        if op[0] == "snapshot":
            outcomes.append("snap")
        else:
            conflict = prov.commit_batch([(op[1], op[2], "ks")])[0]
            outcomes.append(conflict is None)
    return outcomes, prov.consumed_digest()


KILL_SCHEDULES = [
    pytest.param(
        (("durability.wal.pre_fsync", 5),), 5, id="pre-fsync-torn-tail"
    ),
    pytest.param(
        (("durability.snapshot.rename", 1),), 0, id="mid-snapshot"
    ),
    pytest.param(
        (("durability.wal.pre_fsync", 4),
         ("durability.wal.post_fsync", 9),
         ("durability.snapshot.rename", 2),
         ("durability.compact", 2)),
        0, id="kill-storm-all-sites",
    ),
]


class TestDurableRecovery:
    def test_restart_rebuilds_device_table(self, tmp_path):
        """Restart-from-directory: snapshot + WAL replay repopulate the
        shadow AND the device table (statestore.rebuild_rows), the
        digest matches the pre-restart one bit-for-bit, and recovered
        double-spend checks are answered by DEVICE probes."""
        base = str(tmp_path)
        dev = DeviceShardedUniquenessProvider(
            DurableStore(base, name="ss", snapshot_every=1 << 30),
            slots_per_shard=64, max_probe=8,
        )
        reqs = [
            ([_ref(70000 + 2 * i), _ref(70000 + 2 * i + 1)],
             _tx(70000 + i), "p")
            for i in range(12)
        ]
        dev.commit_batch(reqs[:6])
        dev.snapshot_now()
        dev.commit_batch(reqs[6:])
        digest = dev.consumed_digest()
        dev.close()

        before = _counters()
        dev2 = DeviceShardedUniquenessProvider(
            DurableStore(base, name="ss", snapshot_every=1 << 30),
            slots_per_shard=64, max_probe=8,
        )
        try:
            assert dev2.last_recovery is not None
            assert dev2.last_recovery.replayed >= 6
            d = _delta(before)
            assert d.get("statestore.rebuild_rows", 0) == 24
            assert dev2._table.n_live + dev2.spill_count() == 24
            assert dev2.consumed_digest() == digest
            assert dev2.device_divergence() == 0
            # the recovered DEVICE table answers the conflict check
            probe_before = _counters()
            got = dev2.commit_batch(
                [([_ref(70000)], _tx(79999), "mallory")]
            )
            assert got[0] is not None
            assert _delta(probe_before).get(
                "statestore.probe_rows", 0
            ) >= 1
            # and a fresh commit still lands
            assert dev2.commit_batch(
                [([_ref(71000)], _tx(71000), "p")]
            ) == [None]
        finally:
            dev2.close()

    @pytest.mark.parametrize("schedule,torn_cut", KILL_SCHEDULES)
    def test_kill_storm_matches_host_oracle(self, tmp_path, schedule,
                                            torn_cut):
        """The PR 17 crash-recovery acceptance: the durable statestore
        killed at PR 10's crash sites (incl. a torn WAL tail) loses no
        acked commit, admits no double-spend, and the REBUILT device
        table's consumed_digest() matches the never-crashed host-map
        oracle bit-for-bit."""
        oracle_outcomes, oracle_digest = _drive_host_oracle()
        outcomes, digest, crashes, prov = _drive_device(
            str(tmp_path), schedule=schedule, torn_cut=torn_cut
        )
        try:
            assert crashes == len(schedule), (
                "a scheduled crash site never fired — the schedule does "
                "not cross the code path it claims to kill"
            )
            assert outcomes == oracle_outcomes
            assert digest == oracle_digest
            assert prov.device_divergence() == 0
            # the recovered provider still rejects a fresh double-spend
            with pytest.raises(NotaryError):
                prov.commit([_ref(60000)], _tx(60902), "mallory")
        finally:
            prov.close()


# ------------------------------------------------------ vault index tier

@dataclasses.dataclass(frozen=True)
class SSCoin:
    amount: Amount
    owner: Party

    @property
    def participants(self):
        return [self.owner]


@dataclasses.dataclass(frozen=True)
class SSCoinCmd:
    op: str = "issue"


register_custom(
    SSCoin, "test.ss.Coin",
    to_fields=lambda s: {"q": s.amount.quantity, "t": s.amount.token,
                         "o": s.owner},
    from_fields=lambda d: SSCoin(Amount(d["q"], d["t"]), d["o"]),
)
register_custom(
    SSCoinCmd, "test.ss.CoinCmd",
    to_fields=lambda c: {"op": c.op},
    from_fields=lambda d: SSCoinCmd(d["op"]),
)


@register_contract("test.ss.CoinContract")
class SSCoinContract:
    def verify(self, tx):
        pass


def _party(name: str):
    kp = generate_keypair()
    return Party(CordaX500Name(name, "London", "GB"), kp.public), kp


def _issue(owner, notary_party, notary_kp, quantity=100, n_outputs=1):
    b = TransactionBuilder(notary=notary_party)
    for _ in range(n_outputs):
        b.add_output_state(
            SSCoin(Amount(quantity, "GBP"), owner), "test.ss.CoinContract"
        )
    b.add_command(SSCoinCmd("issue"), owner.owning_key)
    return b.sign_initial_transaction(notary_kp)


class TestVaultIndex:
    @pytest.fixture(scope="class")
    def parties(self):
        return _party("SS Alice"), _party("SS Bob"), _party("SS Notary")

    def test_record_consume_membership_and_owner_counts(self, parties):
        (alice, alice_kp), (bob, _bob_kp), (notary, notary_kp) = parties
        index = DeviceVaultIndex(slots_per_shard=64, max_probe=8)
        vault = NodeVaultService(observe_all=True, state_index=index)
        before = _counters()
        vault.record_transaction(
            _issue(alice, notary, notary_kp, n_outputs=3)
        )
        refs = [sr.ref for sr in vault.unconsumed_states(SSCoin)]
        assert len(refs) == 3
        assert index.contains(refs).all()
        assert index.owner_count(alice.owning_key) == 3
        assert index.owner_count(bob.owning_key) == 0
        assert vault.unconsumed_ref_exists(refs[0])
        fake = StateRef(_tx(80000), 7)
        assert not vault.unconsumed_ref_exists(fake)
        # spend one: alice -> bob consumes a ref, produces bob's
        b = TransactionBuilder(notary=notary)
        sr = vault.unconsumed_states(SSCoin)[0]
        b.add_input_state(sr)
        b.add_output_state(
            SSCoin(Amount(100, "GBP"), bob), "test.ss.CoinContract"
        )
        b.add_command(SSCoinCmd("move"), alice.owning_key)
        vault.record_transaction(b.sign_initial_transaction(alice_kp))
        assert not index.contains([sr.ref])[0]
        assert not vault.unconsumed_ref_exists(sr.ref)
        assert index.owner_count(alice.owning_key) == 2
        assert index.owner_count(bob.owning_key) == 1
        # coin selection cross-check: SQL picks are device-present
        picked = vault.select_fungible("GBP", 150, "flow-ss", SSCoin)
        assert len(picked) >= 2
        d = _delta(before)
        assert d.get("statestore.vault.select_mismatch", 0) == 0
        assert d.get("statestore.vault.adds", 0) == 4
        assert d.get("statestore.vault.removes", 0) == 1

    def test_probe_fault_degrades_to_sql(self, parties):
        (alice, _kp), _bob, (notary, notary_kp) = parties
        index = DeviceVaultIndex(slots_per_shard=64, max_probe=8)
        vault = NodeVaultService(observe_all=True, state_index=index)
        vault.record_transaction(_issue(alice, notary, notary_kp))
        ref = vault.unconsumed_states(SSCoin)[0].ref
        before = _counters()
        install_injector(FaultInjector(FaultPlan(
            seed=6, fail_sites=(("statestore.probe", 1),),
        )))
        try:
            assert index.contains([ref]) is None
            # the vault helper still answers correctly — from SQL
            install_injector(FaultInjector(FaultPlan(
                seed=6, fail_sites=(("statestore.probe", 1),),
            )))
            assert vault.unconsumed_ref_exists(ref)
        finally:
            clear_injector()
        assert _delta(before).get(
            "statestore.vault.probe_failover", 0
        ) == 2

    def test_journal_recovery_repopulates_index(self, parties, tmp_path):
        (alice, _kp), _bob, (notary, notary_kp) = parties
        store = DurableStore(str(tmp_path), name="vault")
        vault = NodeVaultService(
            observe_all=True, journal=store,
        )
        vault.record_transaction(_issue(alice, notary, notary_kp))
        ref = vault.unconsumed_states(SSCoin)[0].ref
        store.flush()
        store.close()
        # restart: the index is attached BEFORE journal recovery, so
        # replay repopulates it beside the SQL pages
        index = DeviceVaultIndex(slots_per_shard=64, max_probe=8)
        vault2 = NodeVaultService(
            observe_all=True,
            journal=DurableStore(str(tmp_path), name="vault"),
            state_index=index,
        )
        assert index.contains([ref])[0]
        assert vault2.unconsumed_ref_exists(ref)

    def _spend(self, vault, spender, spender_kp, to, notary):
        b = TransactionBuilder(notary=notary)
        sr = vault.unconsumed_states(SSCoin)[0]
        b.add_input_state(sr)
        b.add_output_state(
            SSCoin(Amount(100, "GBP"), to), "test.ss.CoinContract"
        )
        b.add_command(SSCoinCmd("move"), spender.owning_key)
        vault.record_transaction(b.sign_initial_transaction(spender_kp))
        return sr.ref

    def test_file_backed_replay_does_not_resurrect_spent_refs(
            self, parties, tmp_path):
        """Journal replay over an ALREADY-APPLIED file-backed vault: the
        SQL re-insert is ignored (rowcount 0) and the consumed=0 lookup
        misses, yet the device index must still converge — spent refs
        stay out, live refs stay in."""
        (alice, alice_kp), (bob, _), (notary, notary_kp) = parties
        db = str(tmp_path / "vault.db")
        vault = NodeVaultService(
            db, observe_all=True,
            journal=DurableStore(str(tmp_path), name="vault"),
        )
        vault.record_transaction(_issue(alice, notary, notary_kp, n_outputs=2))
        spent = self._spend(vault, alice, alice_kp, bob, notary)
        live = [sr.ref for sr in vault.unconsumed_states(SSCoin)]
        vault.close()
        # restart: vault.db already holds every row, so the WAL tail
        # replays over applied state
        index = DeviceVaultIndex(slots_per_shard=64, max_probe=8)
        vault2 = NodeVaultService(
            db, observe_all=True,
            journal=DurableStore(str(tmp_path), name="vault"),
            state_index=index,
        )
        assert not index.contains([spent])[0]
        assert not vault2.unconsumed_ref_exists(spent)
        assert index.contains(live).all()
        for ref in live:
            assert vault2.unconsumed_ref_exists(ref)

    def test_snapshot_restore_populates_index(self, parties, tmp_path):
        """States restored through the page snapshot (_load_pages writes
        SQL directly, bypassing record_transaction) must still land in
        the device index — a confident False for a live state is the one
        answer the index may never give."""
        (alice, _kp), _bob, (notary, notary_kp) = parties
        store = DurableStore(str(tmp_path), name="vault")
        vault = NodeVaultService(observe_all=True, journal=store)
        vault.record_transaction(_issue(alice, notary, notary_kp, n_outputs=3))
        refs = [sr.ref for sr in vault.unconsumed_states(SSCoin)]
        vault.snapshot_now()
        vault.close()
        index = DeviceVaultIndex(slots_per_shard=64, max_probe=8)
        before = _counters()
        vault2 = NodeVaultService(
            observe_all=True,
            journal=DurableStore(str(tmp_path), name="vault"),
            state_index=index,
        )
        assert index.contains(refs).all()
        for ref in refs:
            assert vault2.unconsumed_ref_exists(ref)
        # the coin-selection cross-check agrees with SQL
        vault2.select_fungible("GBP", 150, "flow-snap", SSCoin)
        assert _delta(before).get(
            "statestore.vault.select_mismatch", 0
        ) == 0

    def test_spilled_key_never_dual_resident(self):
        """A key in the spill tier is never re-offered to the device: a
        later remove must clear BOTH tiers, or the consumed ref would
        report unconsumed forever from the stale spill entry."""
        from corda_tpu.notary.uniqueness import _ref_key

        index = DeviceVaultIndex(slots_per_shard=8, max_probe=2)
        refs = [_ref(91000 + i) for i in range(64)]
        index.add_states([(r, None) for r in refs])
        assert index.stats()["spill_rows"] > 0
        spilled_keys = set(index._spill)
        spilled = [r for r in refs if _ref_key(r) in spilled_keys]
        resident = [r for r in refs if _ref_key(r) not in spilled_keys]
        # open device room, then re-offer the whole set (idempotent
        # re-record shape) — spilled keys must NOT migrate onto device
        index.remove_states(resident[: len(resident) // 2])
        index.add_states([(r, None) for r in refs])
        assert set(index._spill) == spilled_keys
        # consuming the spilled refs clears them from every tier
        index.remove_states(spilled)
        assert not index.contains(spilled).any()
        assert index.stats()["spill_rows"] == 0

    def test_lost_table_poisons_and_vault_degrades_to_sql(self, parties):
        """A donated dispatch that dies after deleting the table arrays
        latches the table poisoned (statestore.table_lost); the vault
        index degrades: probes fall back to SQL, adds spill, removes
        still clear the spill tier."""
        from corda_tpu.statestore import DeviceTableLostError

        (alice, alice_kp), (bob, _), (notary, notary_kp) = parties
        index = DeviceVaultIndex(slots_per_shard=64, max_probe=8)
        vault = NodeVaultService(observe_all=True, state_index=index)
        vault.record_transaction(_issue(alice, notary, notary_kp, n_outputs=2))
        refs = [sr.ref for sr in vault.unconsumed_states(SSCoin)]
        before = _counters()
        # simulate the aborted donated step: the buffers are gone
        table = index._table
        table._keys.delete()
        table._mark_poisoned_if_lost()
        assert table.stats()["poisoned"]
        with pytest.raises(DeviceTableLostError):
            table.probe_rows(key_rows([b"x" * 36]))
        # membership degrades to SQL, still correct
        assert index.contains(refs) is None
        assert vault.unconsumed_ref_exists(refs[0])
        # recording still works: removes clear spill, adds spill host-side
        spent = self._spend(vault, alice, alice_kp, bob, notary)
        assert not vault.unconsumed_ref_exists(spent)
        new_ref = [
            sr.ref for sr in vault.unconsumed_states(SSCoin)
            if sr.ref != refs[1]
        ][0]
        assert vault.unconsumed_ref_exists(new_ref)
        d = _delta(before)
        assert d.get("statestore.table_lost", 0) == 1
        assert d.get("statestore.vault.add_failover", 0) >= 1
        assert d.get("statestore.vault.remove_failover", 0) >= 1


# --------------------------------------------------- serving fusion tier

class TestMegaScreenFusion:
    def test_screen_counts_device_resident_hits(self):
        import jax.numpy as jnp

        dev = DeviceShardedUniquenessProvider(
            slots_per_shard=64, max_probe=8
        )
        try:
            assert active_mega_screen() is not None
            reqs = [([_ref(81000 + i)], _tx(81000 + i), "p")
                    for i in range(6)]
            dev.commit_batch(reqs)
            present = key_rows([
                ref.txhash.bytes + ref.index.to_bytes(4, "big")
                for (states, _t, _c) in reqs for ref in states
            ])
            absent = key_rows([b"ss-not-there-%d" % i for i in range(2)])
            rows = jnp.asarray(np.concatenate([present, absent]))
            hits = int(active_mega_screen()(rows, rows.shape[0]))
            assert hits == 6
            # the padding tail beyond n is excluded from the count
            assert int(active_mega_screen()(rows, 3)) == 3
        finally:
            dev.close()
        # close() unregisters the screen
        assert active_mega_screen() is None

    def test_collect_harvests_screen_counters(self):
        import jax.numpy as jnp

        from corda_tpu.serving.scheduler import _MeshPending

        before = _counters()
        pending = _MeshPending(
            [(None, None, b"m")] * 3, np.array([True, True, False]),
            None, 2, bucket=4,
        )
        pending.statestore_hits = jnp.int32(2)
        assert pending.collect().tolist() == [True, True, False]
        d = _delta(before)
        assert d.get("statestore.mega_probe_rows", 0) == 3
        assert d.get("statestore.mega_probe_hits", 0) == 2

    def test_monitoring_section_reports_tables(self):
        # tables were built by earlier tests in this process
        section = statestore_section()
        assert section["enabled"] is True
        names = {t["name"] for t in section["tables"]}
        assert "uniqueness" in names
        from corda_tpu.node.monitoring import monitoring_snapshot

        snap = monitoring_snapshot()
        assert snap["statestore"]["enabled"] is True
        assert not any(
            k.startswith("statestore.") for k in snap["process"]
        )


# ------------------------------------- satellite: single-pass InMemory

class _CountingLock:
    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self.acquisitions = 0

    def __enter__(self):
        self._lock.acquire()
        self.acquisitions += 1
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False


class TestInMemorySinglePass:
    def _requests(self):
        a, b, c = _ref(82001), _ref(82002), _ref(82003)
        return [
            ([a, b], _tx(82001), "p1"),          # fresh, multi-ref
            ([c], _tx(82002), "p2"),             # fresh
            ([a], _tx(82003), "thief"),          # intra-batch conflict
            ([a, b], _tx(82001), "p1"),          # idempotent retry
            ([b, c], _tx(82004), "thief2"),      # conflicts BOTH priors
            ([], _tx(82005), "empty"),
        ]

    def test_single_lock_acquisition(self):
        prov = InMemoryUniquenessProvider()
        lock = _CountingLock()
        prov._lock = lock
        out = prov.commit_batch(self._requests())
        assert lock.acquisitions == 1
        assert [o is None for o in out] == [
            True, True, False, True, False, True
        ]

    def test_batch_verdicts_identical_to_per_request_loop(self):
        batch = InMemoryUniquenessProvider()
        got = batch.commit_batch(self._requests())
        loop = InMemoryUniquenessProvider()
        want = []
        for states, tx_id, caller in self._requests():
            try:
                loop.commit(states, tx_id, caller)
                want.append(None)
            except NotaryError as e:
                want.append(e.conflict)
        _assert_verdicts_equal(want, got)
        assert batch.consumed_digest() == loop.consumed_digest()


# -------------------------------- satellite: streamed ledger generators

class TestGeneratedStreams:
    def test_stream_commit_requests_is_seed_deterministic(self):
        def take(n):
            return [
                (r.refs, r.tx_id, r.expect_conflict)
                for r in itertools.islice(
                    stream_commit_requests(
                        seed=5, n_states=10**9,
                        double_spend_fraction=0.05,
                    ), n,
                )
            ]

        assert take(400) == take(400)

    def test_flagged_double_spends_conflict_and_nothing_else(self):
        prov = InMemoryUniquenessProvider()
        n_conflicts = 0
        for req in stream_commit_requests(
            seed=9, n_states=3000, double_spend_fraction=0.05,
            max_frontier=64,
        ):
            verdict = prov.commit_batch(
                [(list(req.refs), req.tx_id, req.caller)]
            )[0]
            assert (verdict is not None) == req.expect_conflict, req
            n_conflicts += req.expect_conflict
        assert n_conflicts > 10

    def test_generated_ledger_stream_is_memory_bounded(self):
        gen = GeneratedLedger(seed=3)
        seen = 0
        for stx in gen.stream(30, max_unspent=16):
            assert stx.sigs
            seen += 1
        assert seen == 30
        # streamed txs are NOT retained and the frontier stays capped
        assert not gen.transactions
        assert len(gen.unspent) <= 16

    @pytest.mark.slow
    def test_ten_million_state_ledger_scale(self):
        """Satellite 2 acceptance: the streamed generator builds a
        10^7-state ledger with bounded memory while the conflict checks
        run on every request; every deliberately-flagged double-spend is
        rejected and no legitimate request conflicts."""
        prov = InMemoryUniquenessProvider()
        conflicts = 0
        batch = []

        def settle(batch):
            got = prov.commit_batch(
                [(list(r.refs), r.tx_id, r.caller) for r in batch]
            )
            n = 0
            for r, verdict in zip(batch, got):
                assert (verdict is not None) == r.expect_conflict
                n += r.expect_conflict
            return n
        for req in stream_commit_requests(
            seed=2026, n_states=10**7, double_spend_fraction=0.002,
            max_frontier=8192,
        ):
            batch.append(req)
            if len(batch) == 4096:
                conflicts += settle(batch)
                batch = []
        if batch:
            conflicts += settle(batch)
        assert conflicts > 1000
        assert prov.committed_txs() > 10**6


# ---------------------------------------------------- off-by-default pin

class TestOffByDefault:
    def test_fresh_subprocess_zero_overhead(self):
        """Fresh subprocess, CORDA_TPU_STATESTORE unset: the statestore
        package imports WITHOUT jax, allocates no tables, registers no
        statestore.* metrics, the vault attaches no index, the serving
        hook reads None, and the monitoring section is the off marker."""
        code = """
import json, os, sys
os.environ.pop("CORDA_TPU_STATESTORE", None)
import corda_tpu.statestore as ss
assert not ss.statestore_enabled()
assert "jax" not in sys.modules, "statestore import pulled in jax"
assert ss.statestore_section() == {"enabled": False}
assert ss.maybe_vault_index() is None
assert ss.active_mega_screen() is None
from corda_tpu.node import NodeVaultService
from corda_tpu.node.monitoring import monitoring_snapshot, node_metrics
vault = NodeVaultService(observe_all=True)
assert vault._state_index is None
assert monitoring_snapshot()["statestore"] == {"enabled": False}
assert not any(
    n.startswith("statestore.") for n in node_metrics().snapshot()
)
print(json.dumps({"ok": True}))
"""
        env = {k: v for k, v in os.environ.items()
               if k != "CORDA_TPU_STATESTORE"}
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=120, env=env,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout.strip().splitlines()[-1]) == {
            "ok": True
        }

    def test_env_gate_enables_vault_index_and_notary_reexport(self):
        """CORDA_TPU_STATESTORE=1 in a fresh subprocess: the gate reads
        on, maybe_vault_index builds a device index, the notary package
        re-exports the provider, and the monitoring section reports the
        table."""
        code = """
import json
import corda_tpu.statestore as ss
assert ss.statestore_enabled()
idx = ss.maybe_vault_index()
from corda_tpu.statestore import DeviceVaultIndex
assert isinstance(idx, DeviceVaultIndex)
from corda_tpu.notary import DeviceShardedUniquenessProvider
from corda_tpu.statestore import provider as _p
assert DeviceShardedUniquenessProvider \
    is _p.DeviceShardedUniquenessProvider
section = ss.statestore_section()
assert section["enabled"] is True
assert section["tables"][0]["name"] == "vault"
print(json.dumps({"ok": True}))
"""
        env = dict(os.environ)
        env["CORDA_TPU_STATESTORE"] = "1"
        env["CORDA_TPU_STATESTORE_SLOTS"] = "64"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=180, env=env,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout.strip().splitlines()[-1]) == {
            "ok": True
        }
