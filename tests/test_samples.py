"""Sample CorDapp + node-container tests — the reference's samples/ test
coverage (TraderDemoTest, attachment-demo tests, NodeInterestRatesTest
oracle tear-off tests, notary-demo) plus AbstractNode assembly."""

import pytest

from corda_tpu.samples import (
    attachment_demo,
    bank_demo,
    notary_demo,
    oracle_demo,
    trader_demo,
)


class TestDemos:
    def test_trader_demo(self):
        r = trader_demo.run_demo(verbose=False)
        assert r["buyer_papers"] == 1
        assert r["seller_cash"] == 900

    def test_attachment_demo(self):
        r = attachment_demo.run_demo(verbose=False)
        assert r["recipient_fetched"] and r["content_verified"]

    def test_bank_demo(self):
        r = bank_demo.run_demo(n_requests=2, verbose=False)
        assert r["customer_balance"] == 3000

    def test_oracle_demo(self):
        r = oracle_demo.run_demo(verbose=False)
        assert r["oracle_signed"]
        assert r["wrong_rate_refused"]
        # the privacy property: the oracle saw exactly one component
        assert r["oracle_saw_components"] == 1

    def test_notary_demo_all_tiers(self):
        r = notary_demo.run_demo(n_txs=10, verbose=False)
        for mode in ("single", "raft", "bft"):
            assert r[mode]["double_spend_rejected"], r
            assert r[mode]["notarised"] > 0


class TestNodeContainer:
    def test_assembly_and_flow(self):
        """Node built from NodeConfiguration runs the full cash path
        (reference: AbstractNode.start + NodePerformanceTests shape)."""
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow, CashState
        from corda_tpu.ledger import CordaX500Name
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.node import NetworkMapCache, Node, NodeConfiguration
        from corda_tpu.node.config import NotaryConfig, VerifierType

        net = InMemoryMessagingNetwork()
        net.start_pumping()
        nmap = NetworkMapCache()

        def mk(name, notary=None):
            legal = f"O={name},L=City,C=GB"
            cfg = NodeConfiguration(
                my_legal_name=legal, notary=notary,
                verifier_type=VerifierType.InMemory,
            )
            endpoint = net.create_node(str(CordaX500Name.parse(legal)))
            return Node(cfg, endpoint, network_map=nmap).start()

        alice = mk("Alice")
        bob = mk("Bob")
        notary = mk("Notary", NotaryConfig(validating=True))
        try:
            # notary advertised through the map with its mode
            assert nmap.is_validating_notary(notary.party)
            alice.run_flow(CashIssueFlow(500, "GBP", b"\x01", notary.party))
            alice.run_flow(CashPaymentFlow(200, "GBP", bob.party))
            got = sum(
                sr.state.data.amount.quantity
                for sr in bob.services.vault_service.unconsumed_states(
                    CashState
                )
            )
            assert got == 200
        finally:
            for n in (alice, bob, notary):
                n.stop()
            net.stop_pumping()

    def test_wrong_transport_name_rejected(self):
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.node import Node, NodeConfiguration

        net = InMemoryMessagingNetwork()
        cfg = NodeConfiguration(my_legal_name="O=Alice,L=City,C=GB")
        with pytest.raises(ValueError, match="address"):
            Node(cfg, net.create_node("wrong-name"))

    def test_config_file_to_node(self, tmp_path):
        """HOCON config file → assembled node (reference: NodeStartup
        loadConfigFile path)."""
        from corda_tpu.ledger import CordaX500Name
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.node import Node
        from corda_tpu.node.config import load_config

        conf = tmp_path / "node.conf"
        conf.write_text("""
            myLegalName = "O=Config Node,L=Paris,C=FR"
            notary { validating = true }
            rpcUsers = [{ username = "u", password = "p", permissions = ["ALL"] }]
        """)
        cfg = load_config(conf)
        net = InMemoryMessagingNetwork()
        endpoint = net.create_node(
            str(CordaX500Name.parse(cfg.my_legal_name))
        )
        node = Node(cfg, endpoint).start()
        try:
            assert node.services.notary_service is not None
            assert node.config.rpc_users[0].username == "u"
        finally:
            node.stop()
