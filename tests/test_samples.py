"""Sample CorDapp + node-container tests — the reference's samples/ test
coverage (TraderDemoTest, attachment-demo tests, NodeInterestRatesTest
oracle tear-off tests, notary-demo) plus AbstractNode assembly."""

import pytest

from corda_tpu.samples import (
    attachment_demo,
    bank_demo,
    network_visualiser,
    notary_demo,
    oracle_demo,
    simm_demo,
    trader_demo,
)


class TestDemos:
    def test_trader_demo(self):
        r = trader_demo.run_demo(verbose=False)
        assert r["buyer_papers"] == 1
        assert r["seller_cash"] == 900

    def test_trader_demo_concurrent_trades(self):
        """The load shape that broke round-3's first cut: many DvP trades
        in flight at once. Regression-pins three engine properties —
        (a) a PARKED wait_for_ledger_commit wakes when the broadcast
        records (commit listener, engine.py); (b) ResolveTransactionsFlow
        replays deterministically while its own recordings mutate storage
        (recorded frontiers); (c) soft locks survive park-unwind (engine-
        managed release), so concurrent buyers never double-spend."""
        import time as _time

        from corda_tpu.finance import CashIssueFlow
        from corda_tpu.ledger import StateRef
        from corda_tpu.testing import MockNetworkNodes

        n = 12
        with MockNetworkNodes() as net:
            bank = net.create_node("Bank A")
            buyer = net.create_node("Bank B")
            notary = net.create_notary_node("Notary", validating=True)
            papers = []
            for _ in range(n):
                buyer.run_flow(
                    CashIssueFlow(1500, "GBP", b"\x01", notary.party)
                )
                issued = trader_demo.issue_paper(bank, notary.party)
                papers.append(
                    bank.services.to_state_and_ref(StateRef(issued.id, 0))
                )
            handles = [
                bank.smm.start_flow(
                    trader_demo.SellerFlow(buyer.party, sar, 900, "GBP")
                )
                for sar in papers
            ]
            for h in handles:
                stx = h.result.result(timeout=120)
                assert stx is not None
            # sellers all completed (none left parked), and the engine
            # released the buyer's selection locks at flow completion —
            # every 1500-state was spendable exactly once
            deadline = _time.monotonic() + 10
            while (bank.smm.flows_in_progress()
                   and _time.monotonic() < deadline):
                _time.sleep(0.05)
            assert bank.smm.flows_in_progress() == []
            from corda_tpu.finance import CashState

            seller_cash = sum(
                sr.state.data.amount.quantity
                for sr in bank.services.vault_service.unconsumed_states(
                    CashState
                )
            )
            assert seller_cash == 900 * n

    def test_attachment_demo(self):
        r = attachment_demo.run_demo(verbose=False)
        assert r["recipient_fetched"] and r["content_verified"]

    def test_simm_demo(self):
        r = simm_demo.run_demo(verbose=False)
        assert r["portfolio_recorded_both_sides"]
        assert r["initial_margin_cents"] > 0

    def test_simm_consensus_rejects_divergent_valuation(self):
        """A responder that computes a different margin must refuse to
        sign (the consensus property SimmFlow exists for)."""
        m1 = simm_demo.initial_margin_cents([
            simm_demo.SwapData("s1", 1_000_000, 150, 5.0, buy=True),
            simm_demo.SwapData("s2", 2_000_000, 140, 10.0, buy=False),
        ])
        m2 = simm_demo.initial_margin_cents([
            simm_demo.SwapData("s1", 1_000_000, 150, 5.0, buy=True),
        ])
        assert m1 != m2  # engine is direction/size-sensitive
        # deterministic across independent computations
        assert m1 == simm_demo.initial_margin_cents([
            simm_demo.SwapData("s1", 1_000_000, 150, 5.0, buy=True),
            simm_demo.SwapData("s2", 2_000_000, 140, 10.0, buy=False),
        ])

    def test_network_visualiser_demo(self, tmp_path):
        r = network_visualiser.run_demo(out_dir=str(tmp_path), verbose=False)
        assert r["messages"] > 10 and r["nodes"] == 3
        dot = (tmp_path / "network.dot").read_text()
        assert "digraph" in dot and "Notary" in dot
        assert (tmp_path / "network.html").read_text().startswith("<!DOCTYPE")

    def test_bank_demo(self):
        r = bank_demo.run_demo(n_requests=2, verbose=False)
        assert r["customer_balance"] == 3000

    def test_oracle_demo(self):
        r = oracle_demo.run_demo(verbose=False)
        assert r["oracle_signed"]
        assert r["wrong_rate_refused"]
        # the privacy property: the oracle saw exactly one component
        assert r["oracle_saw_components"] == 1

    def test_notary_demo_all_tiers(self):
        r = notary_demo.run_demo(n_txs=10, verbose=False)
        for mode in ("single", "raft", "bft"):
            assert r[mode]["double_spend_rejected"], r
            assert r[mode]["notarised"] > 0


class TestNodeContainer:
    def test_assembly_and_flow(self):
        """Node built from NodeConfiguration runs the full cash path
        (reference: AbstractNode.start + NodePerformanceTests shape)."""
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow, CashState
        from corda_tpu.ledger import CordaX500Name
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.node import NetworkMapCache, Node, NodeConfiguration
        from corda_tpu.node.config import NotaryConfig, VerifierType

        net = InMemoryMessagingNetwork()
        net.start_pumping()
        nmap = NetworkMapCache()

        def mk(name, notary=None):
            legal = f"O={name},L=City,C=GB"
            cfg = NodeConfiguration(
                my_legal_name=legal, notary=notary,
                verifier_type=VerifierType.InMemory,
            )
            endpoint = net.create_node(str(CordaX500Name.parse(legal)))
            return Node(cfg, endpoint, network_map=nmap).start()

        alice = mk("Alice")
        bob = mk("Bob")
        notary = mk("Notary", NotaryConfig(validating=True))
        try:
            # notary advertised through the map with its mode
            assert nmap.is_validating_notary(notary.party)
            alice.run_flow(CashIssueFlow(500, "GBP", b"\x01", notary.party))
            alice.run_flow(CashPaymentFlow(200, "GBP", bob.party))
            got = sum(
                sr.state.data.amount.quantity
                for sr in bob.services.vault_service.unconsumed_states(
                    CashState
                )
            )
            assert got == 200
        finally:
            for n in (alice, bob, notary):
                n.stop()
            net.stop_pumping()

    def test_wrong_transport_name_rejected(self):
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.node import Node, NodeConfiguration

        net = InMemoryMessagingNetwork()
        cfg = NodeConfiguration(my_legal_name="O=Alice,L=City,C=GB")
        with pytest.raises(ValueError, match="address"):
            Node(cfg, net.create_node("wrong-name"))

    def test_config_file_to_node(self, tmp_path):
        """HOCON config file → assembled node (reference: NodeStartup
        loadConfigFile path)."""
        from corda_tpu.ledger import CordaX500Name
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.node import Node
        from corda_tpu.node.config import load_config

        conf = tmp_path / "node.conf"
        conf.write_text("""
            myLegalName = "O=Config Node,L=Paris,C=FR"
            notary { validating = true }
            rpcUsers = [{ username = "u", password = "p", permissions = ["ALL"] }]
        """)
        cfg = load_config(conf)
        net = InMemoryMessagingNetwork()
        endpoint = net.create_node(
            str(CordaX500Name.parse(cfg.my_legal_name))
        )
        node = Node(cfg, endpoint).start()
        try:
            assert node.services.notary_service is not None
            assert node.config.rpc_users[0].username == "u"
        finally:
            node.stop()
