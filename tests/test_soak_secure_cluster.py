"""Secure-fabric cluster soak (r3 VERDICT task 8).

One composed scenario over real processes: a 3-replica Raft notary
cluster and two dealers ride the mutually-authenticated fabric, a payment
storm runs against the cluster, and mid-storm a Raft replica AND an
out-of-process verifier worker are killed — the replica is then restarted
and must rejoin from its durable state. Asserts no lost commits (every
payment completes), no duplicate commits (balances reconcile exactly),
and throughput recovery (a post-restart wave completes like the first).

Reference shape: Disruption.kt (kill-the-node disruptions under loadtest)
+ VerifierTests.kt:55-113 (worker death redistributes work) + the
raft-notary demo's cluster.
"""

import time
from concurrent.futures import TimeoutError as FutureTimeoutError

import pytest

from corda_tpu.flows.api import class_path
from corda_tpu.ledger import CordaX500Name
from corda_tpu.testing import driver


@pytest.mark.slow
class TestSecureClusterSoak:
    def test_storm_survives_replica_and_worker_crash(self, tmp_path):
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow

        raft_names = [
            "O=Raft0,L=Zurich,C=CH",
            "O=Raft1,L=Zurich,C=CH",
            "O=Raft2,L=Zurich,C=CH",
        ]
        canon = [str(CordaX500Name.parse(n)) for n in raft_names]
        with driver(str(tmp_path), secure=True) as dsl:
            # Raft0 also serves the fabric + network map (driver harness
            # shape) — the replicas we crash are Raft1/Raft2
            notaries = [
                dsl.start_node(n, notary=True, raft_cluster=tuple(canon),
                               timeout_s=90)
                for n in raft_names
            ]
            alice = dsl.start_node(
                "O=Alice,L=London,C=GB", timeout_s=90,
                extra_config='verifierType = "OutOfProcess"',
            )
            bob = dsl.start_node("O=Bob,L=Rome,C=IT", timeout_s=90)
            worker1 = dsl.start_verifier_worker("soak-worker-1")
            worker2 = dsl.start_verifier_worker("soak-worker-2")

            conn = dsl.rpc(alice)
            bconn = dsl.rpc(bob)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                ids = conn.proxy.notary_identities()
                if (len(ids) >= 3
                        and len(conn.proxy.network_map_snapshot()) >= 5):
                    break
                time.sleep(0.3)
            ids = conn.proxy.notary_identities()
            assert len(ids) >= 3, f"cluster did not register: {ids}"
            # transactions name Raft0's identity (its process stays up)
            notary_party = next(
                p for p in ids if str(p.name) == canon[0]
            )
            bob_party = conn.proxy.well_known_party_from_x500_name(
                CordaX500Name.parse("O=Bob,L=Rome,C=IT")
            )

            per_wave, amount = 8, 10
            # one state per payment: concurrent payments racing on a
            # single big state would serialize on its soft lock (the
            # first locker's change only lands after its notarisation)
            issue_fids = [
                conn.proxy.start_flow_dynamic(
                    class_path(CashIssueFlow),
                    amount, "GBP", bytes([i]), notary_party,
                )
                for i in range(2 * per_wave)
            ]
            for f in issue_fids:
                conn.proxy.flow_result(f, 120)

            def wave(n):
                fids = [
                    conn.proxy.start_flow_dynamic(
                        class_path(CashPaymentFlow),
                        amount, "GBP", bob_party,
                    )
                    for _ in range(n)
                ]
                t0 = time.monotonic()
                for f in fids:
                    conn.proxy.flow_result(f, 240)
                return time.monotonic() - t0

            # ---- wave 1, with mid-wave crashes -------------------------
            fids = [
                conn.proxy.start_flow_dynamic(
                    class_path(CashPaymentFlow), amount, "GBP", bob_party
                )
                for _ in range(per_wave)
            ]
            time.sleep(1.5)  # let the storm reach the cluster
            notaries[2].kill()   # a Raft replica dies mid-window
            worker1.kill()       # a verifier worker dies mid-window
            for f in fids:       # no lost commits: every payment lands
                conn.proxy.flow_result(f, 240)

            # ---- restart the replica; it must rejoin from durable state
            restarted = dsl.start_node(
                raft_names[2], notary=True, raft_cluster=tuple(canon),
                timeout_s=90,
            )
            assert restarted.alive

            # ---- wave 2: throughput recovery ---------------------------
            wave2_s = wave(per_wave)
            assert wave2_s < 180, f"post-restart wave too slow: {wave2_s:.0f}s"

            # ---- no duplicate/lost commits: balances reconcile exactly -
            deadline = time.monotonic() + 60
            want = 2 * per_wave * amount
            while time.monotonic() < deadline:
                page = bconn.proxy.vault_query_by()
                got = sum(
                    sr.state.data.amount.quantity for sr in page.states
                )
                if got == want:
                    break
                time.sleep(0.5)
            assert got == want, f"bob holds {got}, expected {want}"
            apage = conn.proxy.vault_query_by()
            assert sum(
                sr.state.data.amount.quantity for sr in apage.states
            ) == 0, "alice kept cash that was spent"


@pytest.mark.slow
class TestSeededChaosSoak:
    """Seeded chaos soak (ISSUE 1 tentpole acceptance): a FaultPlan drives
    drop + delay + duplicate + one scheduled replica crash/restart against
    a durable 3-replica Raft notary cluster while a commit storm (with
    deliberate client re-submissions and double-spend attempts) runs.
    The run must end with every honest commit applied exactly once, every
    double-spend rejected, and bit-identical uniqueness state on all
    replicas — and the plan must actually have injected faults.

    The lock-order sanitizer (observability/lockwatch, ISSUE 6) is
    installed for the whole storm: every lock the cluster constructs is
    watched, and the run additionally asserts an EMPTY cycle report —
    chaos interleavings are exactly when an A→B/B→A inversion would
    surface."""

    def test_chaos_storm_converges_to_identical_state(self, tmp_path):
        from corda_tpu.observability import lockwatch

        # watch every lock the cluster is about to construct; the patch
        # must be UNDONE even when cluster setup itself raises, so the
        # whole storm (setup included) runs inside this try
        lockwatch.reset()
        lockwatch.install()
        try:
            self._storm(tmp_path)
        finally:
            lockwatch.uninstall()
            lockwatch.reset()

    def _storm(self, tmp_path):
        from corda_tpu.crypto import SecureHash
        from corda_tpu.faultinject import (
            ChaosOrchestrator,
            CrashEvent,
            FaultInjector,
            FaultPlan,
        )
        from corda_tpu.ledger import StateRef
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.notary import NotaryError, RaftUniquenessProvider
        from corda_tpu.observability import lockwatch

        def ref(n):
            return StateRef(SecureHash(n.to_bytes(2, "big") * 16), 0)

        def tx(n):
            return SecureHash((10_000 + n).to_bytes(2, "big") * 16)

        plan = FaultPlan(
            seed=2026, drop_p=0.08, delay_p=0.12, duplicate_p=0.1,
            crashes=(CrashEvent(at_round=500, node="s1", down_rounds=2500),),
        )
        inj = FaultInjector(plan)
        net = InMemoryMessagingNetwork(fault_injector=inj)
        orch = ChaosOrchestrator(net, inj)
        names = ["s0", "s1", "s2"]
        storage = str(tmp_path)
        providers = {
            n: RaftUniquenessProvider.make_node(n, names, net, storage)
            for n in names
        }
        for p in providers.values():
            p.node.start()

        def stop_s1():
            providers["s1"].close()
            net.stop_node("s1")

        def restart_s1():
            endpoint = net.restart_node("s1")
            providers["s1"] = RaftUniquenessProvider.make_node_on_endpoint(
                "s1", names, endpoint, storage_path=f"{storage}/s1.db",
                election_timeout_s=(0.15, 0.3), heartbeat_s=0.05,
            )
            providers["s1"].node.start()

        orch.register("s1", stop_s1, restart_s1)
        net.start_pumping()
        n_tx = 40
        try:
            def commit_retrying(provider, refs, tx_id):
                deadline = time.monotonic() + 60
                while True:
                    try:
                        provider.commit(refs, tx_id, "chaos-soak")
                        return None
                    except NotaryError as e:
                        if "already consumed" in str(e):
                            return e
                        if time.monotonic() > deadline:
                            raise
                    except (TimeoutError, FutureTimeoutError):
                        if time.monotonic() > deadline:
                            raise
                    time.sleep(0.05)

            for i in range(n_tx):
                assert commit_retrying(
                    providers["s0"], [ref(i)], tx(i)
                ) is None
                if i % 5 == 0:
                    # client retry of the SAME tx (lost-response replay):
                    # must return the original success, not double-spend
                    assert commit_retrying(
                        providers["s0"], [ref(i)], tx(i)
                    ) is None
                if i % 7 == 0:
                    # a DIFFERENT tx spending the same input must conflict
                    assert commit_retrying(
                        providers["s0"], [ref(i)], tx(1000 + i)
                    ) is not None
                time.sleep(0.01)

            # the scheduled crash must have fired during (or right after)
            # the storm; then wait out the restart
            deadline = time.monotonic() + 90
            while not any(e.kind == "crash" for e in inj.trace):
                assert time.monotonic() < deadline, "crash never fired"
                time.sleep(0.1)
            while "s1" in orch.down:
                assert time.monotonic() < deadline, "s1 never restarted"
                time.sleep(0.1)

            def rows(name):
                return sorted(
                    tuple(
                        bytes(c) if isinstance(c, (bytes, bytearray)) else c
                        for c in row
                    )
                    for row in providers[name].node._storage.dump_map()
                )

            deadline = time.monotonic() + 90
            while True:
                state = [rows(n) for n in names]
                if len(state[0]) == n_tx and state[0] == state[1] == state[2]:
                    break
                assert time.monotonic() < deadline, (
                    "replicas did not converge: "
                    f"{[len(s) for s in state]}"
                )
                time.sleep(0.25)
            # the plan actually exercised the cluster
            kinds = {e.kind for e in inj.trace}
            assert "crash" in kinds and "restart" in kinds
            assert kinds & {"drop", "delay", "duplicate"}
            # the lock-order sanitizer saw the whole storm: any A→B/B→A
            # inversion across the raft/messaging/flow locks is a
            # potential deadlock even though this run survived it
            report = lockwatch.cycle_report()
            assert report == [], (
                "lock-order inversions under chaos: "
                + "; ".join(" -> ".join(c["cycle"]) for c in report)
            )
        finally:
            for p in providers.values():
                try:
                    p.close()
                except Exception:
                    pass
            net.stop_pumping()
