"""Secure-fabric cluster soak (r3 VERDICT task 8).

One composed scenario over real processes: a 3-replica Raft notary
cluster and two dealers ride the mutually-authenticated fabric, a payment
storm runs against the cluster, and mid-storm a Raft replica AND an
out-of-process verifier worker are killed — the replica is then restarted
and must rejoin from its durable state. Asserts no lost commits (every
payment completes), no duplicate commits (balances reconcile exactly),
and throughput recovery (a post-restart wave completes like the first).

Reference shape: Disruption.kt (kill-the-node disruptions under loadtest)
+ VerifierTests.kt:55-113 (worker death redistributes work) + the
raft-notary demo's cluster.
"""

import time
from concurrent.futures import TimeoutError as FutureTimeoutError

import pytest
from conftest import node_process_capability

from corda_tpu.flows.api import class_path
from corda_tpu.ledger import CordaX500Name
from corda_tpu.testing import driver


@pytest.mark.slow
@pytest.mark.skipif(
    bool(node_process_capability()),
    reason=node_process_capability() or "",
)
class TestSecureClusterSoak:
    def test_storm_survives_replica_and_worker_crash(self, tmp_path):
        from conftest import (
            require_driver_ensemble,
            secure_transport_capability,
        )

        if secure_transport_capability():
            pytest.skip(secure_transport_capability())
        require_driver_ensemble()
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow

        raft_names = [
            "O=Raft0,L=Zurich,C=CH",
            "O=Raft1,L=Zurich,C=CH",
            "O=Raft2,L=Zurich,C=CH",
        ]
        canon = [str(CordaX500Name.parse(n)) for n in raft_names]
        with driver(str(tmp_path), secure=True) as dsl:
            # Raft0 also serves the fabric + network map (driver harness
            # shape) — the replicas we crash are Raft1/Raft2
            notaries = [
                dsl.start_node(n, notary=True, raft_cluster=tuple(canon),
                               timeout_s=90)
                for n in raft_names
            ]
            alice = dsl.start_node(
                "O=Alice,L=London,C=GB", timeout_s=90,
                extra_config='verifierType = "OutOfProcess"',
            )
            bob = dsl.start_node("O=Bob,L=Rome,C=IT", timeout_s=90)
            worker1 = dsl.start_verifier_worker("soak-worker-1")
            worker2 = dsl.start_verifier_worker("soak-worker-2")

            conn = dsl.rpc(alice)
            bconn = dsl.rpc(bob)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                ids = conn.proxy.notary_identities()
                if (len(ids) >= 3
                        and len(conn.proxy.network_map_snapshot()) >= 5):
                    break
                time.sleep(0.3)
            ids = conn.proxy.notary_identities()
            assert len(ids) >= 3, f"cluster did not register: {ids}"
            # transactions name Raft0's identity (its process stays up)
            notary_party = next(
                p for p in ids if str(p.name) == canon[0]
            )
            bob_party = conn.proxy.well_known_party_from_x500_name(
                CordaX500Name.parse("O=Bob,L=Rome,C=IT")
            )

            per_wave, amount = 8, 10
            # one state per payment: concurrent payments racing on a
            # single big state would serialize on its soft lock (the
            # first locker's change only lands after its notarisation)
            issue_fids = [
                conn.proxy.start_flow_dynamic(
                    class_path(CashIssueFlow),
                    amount, "GBP", bytes([i]), notary_party,
                )
                for i in range(2 * per_wave)
            ]
            for f in issue_fids:
                conn.proxy.flow_result(f, 120)

            def wave(n):
                fids = [
                    conn.proxy.start_flow_dynamic(
                        class_path(CashPaymentFlow),
                        amount, "GBP", bob_party,
                    )
                    for _ in range(n)
                ]
                t0 = time.monotonic()
                for f in fids:
                    conn.proxy.flow_result(f, 240)
                return time.monotonic() - t0

            # ---- wave 1, with mid-wave crashes -------------------------
            fids = [
                conn.proxy.start_flow_dynamic(
                    class_path(CashPaymentFlow), amount, "GBP", bob_party
                )
                for _ in range(per_wave)
            ]
            time.sleep(1.5)  # let the storm reach the cluster
            notaries[2].kill()   # a Raft replica dies mid-window
            worker1.kill()       # a verifier worker dies mid-window
            for f in fids:       # no lost commits: every payment lands
                conn.proxy.flow_result(f, 240)

            # ---- restart the replica; it must rejoin from durable state
            restarted = dsl.start_node(
                raft_names[2], notary=True, raft_cluster=tuple(canon),
                timeout_s=90,
            )
            assert restarted.alive

            # ---- wave 2: throughput recovery ---------------------------
            wave2_s = wave(per_wave)
            assert wave2_s < 180, f"post-restart wave too slow: {wave2_s:.0f}s"

            # ---- no duplicate/lost commits: balances reconcile exactly -
            deadline = time.monotonic() + 60
            want = 2 * per_wave * amount
            while time.monotonic() < deadline:
                page = bconn.proxy.vault_query_by()
                got = sum(
                    sr.state.data.amount.quantity for sr in page.states
                )
                if got == want:
                    break
                time.sleep(0.5)
            assert got == want, f"bob holds {got}, expected {want}"
            apage = conn.proxy.vault_query_by()
            assert sum(
                sr.state.data.amount.quantity for sr in apage.states
            ) == 0, "alice kept cash that was spent"


@pytest.mark.slow
class TestServingChaosSoak:
    """Self-healing serving plane under seeded chaos (ISSUE 9
    satellite): a storm of device batches runs against an enabled
    devicemon watchdog + quarantine + breaker while the FaultPlan
    schedules STALLS (one long enough for the watchdog's stall rule to
    evict the ordinal mid-flight) and CRASHES at the serving.dispatch /
    verifier.device sites. The whole run — including the hedge timer
    thread — executes under the lock-order sanitizer.

    Asserts: zero lost futures (every one resolves), zero
    doubly-completed futures (each hedge resolved exactly one winner and
    every late readback was discarded — the counter algebra that can
    only hold if completion was single), every verdict identical to the
    host oracle, and an empty lockwatch cycle report."""

    def test_stall_crash_storm_keeps_every_future_exact(self):
        from corda_tpu.observability import lockwatch

        lockwatch.reset()
        lockwatch.install()
        try:
            self._storm()
        finally:
            lockwatch.uninstall()
            lockwatch.reset()

    def _storm(self):
        from corda_tpu.crypto import generate_keypair, is_valid, sign
        from corda_tpu.faultinject import FaultInjector, FaultPlan
        from corda_tpu.faultinject import clear as clear_injector
        from corda_tpu.faultinject import install as install_injector
        from corda_tpu.node.monitoring import node_metrics
        from corda_tpu.observability import configure_devicemon, lockwatch
        from corda_tpu.observability.devicemon import devicemon
        from corda_tpu.serving import (
            DeviceScheduler,
            ResiliencePolicy,
            ShapeTable,
        )

        m = node_metrics()
        names = (
            "serving.hedge.fired", "serving.hedge.won_host",
            "serving.hedge.won_device", "serving.hedge.discarded",
            "serving.quarantine.entered",
            "serving.quarantine.readmitted", "serving.redispatch",
        )
        before = {n: m.counter(n).count for n in names}
        # watchdog fast enough to catch the long stall mid-flight: its
        # eviction reaches the policy through the subscription hook
        configure_devicemon(enabled=True, reset=True, watchdog=True,
                            interval_s=0.05, stall_s=1.0)
        pol = ResiliencePolicy(
            strikes=2, hedge_min_s=0.1, hedge_max_s=0.4,
            probe_backoff_s=0.2, breaker_threshold=8,
            flight_dump_on_quarantine=False,
        )
        sched = DeviceScheduler(
            use_device_default=True,
            shapes=ShapeTable({"buckets": [8, 16, 32],
                               "source": "soak-resilience"}),
            resilience=pol,
        )
        # serving.dispatch nth accounting: b0=1, crash b1=2 (its
        # re-dispatch retries as 3), long stall b2=4; the verifier.device
        # stall lands on whichever bucket dispatch (batch or canary
        # probe) draws nth 6 — chaos either way, both survivable
        inj = install_injector(FaultInjector(FaultPlan(
            seed=2026,
            stall_sites=(
                ("serving.dispatch", 4, 2.5),
                ("verifier.device", 6, 0.5),
            ),
            fail_sites=(("serving.dispatch", 2),),
        )))
        kp = generate_keypair()
        futures, oracles = [], []

        def submit(b):
            rows = []
            for i in range(5):
                msg = b"soak-%d-%d" % (b, i)
                sig = sign(kp.private, msg)
                if (b + i) % 4 == 0:
                    sig = b"\x00" * len(sig)
                rows.append((kp.public, sig, msg))
            oracles.append([is_valid(k, s, mg) for k, s, mg in rows])
            futures.append(sched.submit_rows(rows, use_device=True))
            return futures[-1]

        try:
            # phase A: a clean batch, then a CRASHED one — struck,
            # re-dispatched with original arrival time, its retry heals
            # the suspect with a clean settle
            submit(0).result(timeout=300)
            submit(1).result(timeout=60)
            # phase B: the long stall — hedged to host at ~0.4 s (strike
            # 1), then a QUIET window with the readback still in flight:
            # the watchdog's stall rule evicts the ordinal (strike 2 →
            # quarantine) because nothing else refreshes the heartbeat
            submit(2).result(timeout=60)
            time.sleep(1.6)
            # the eviction must have flowed devicemon → subscription
            # hook → strike before traffic resumes
            kinds = {e["kind"] for e in devicemon().events}
            assert "device.unhealthy" in kinds, kinds
            # phase C: storm on — batches ride host while quarantined,
            # return to device after the canary readmits; verdicts are
            # oracle-identical throughout
            for b in range(3, 12):
                submit(b)
                time.sleep(0.05)
            for fut, oracle in zip(futures, oracles):
                rr = fut.result(timeout=180)
                assert rr.mask.tolist() == oracle, (rr.mask, oracle)
            # every quarantine episode closes: the real canary probes
            # readmit each evicted ordinal
            deadline = time.monotonic() + 90
            while pol.quarantine.active_count() > 0:
                assert time.monotonic() < deadline, (
                    pol.quarantine.snapshot()
                )
                time.sleep(0.1)
        finally:
            clear_injector()
            sched.shutdown()
            configure_devicemon(enabled=False, reset=True,
                                watchdog=False)
        delta = {n: m.counter(n).count - before[n] for n in names}
        # the plan actually exercised the plane
        assert any(e.kind == "op-stall" for e in inj.trace), "no stall"
        assert any(e.kind == "op-fail" for e in inj.trace), "no crash"
        assert delta["serving.hedge.fired"] >= 1, delta
        assert delta["serving.redispatch"] >= 1, delta
        assert delta["serving.quarantine.entered"] >= 1, delta
        # single-completion algebra (post-drain, no hedge unresolved):
        # every fired hedge resolved exactly one winner, and every
        # host-won batch's late readback was discarded exactly once —
        # invariants that can only hold if futures completed once
        assert (delta["serving.hedge.won_host"]
                + delta["serving.hedge.won_device"]
                == delta["serving.hedge.fired"]), delta
        assert delta["serving.hedge.discarded"] \
            == delta["serving.hedge.won_host"], delta
        # quarantine episodes all closed via canary readmission
        assert delta["serving.quarantine.entered"] \
            == delta["serving.quarantine.readmitted"], delta
        # the hedge timer thread passed the runtime lock-order pass: no
        # A→B/B→A inversion anywhere chaos drove the plane
        report = lockwatch.cycle_report()
        assert report == [], (
            "lock-order inversions under serving chaos: "
            + "; ".join(" -> ".join(c["cycle"]) for c in report)
        )


@pytest.mark.slow
class TestSeededChaosSoak:
    """Seeded chaos soak (ISSUE 1 tentpole acceptance): a FaultPlan drives
    drop + delay + duplicate + one scheduled replica crash/restart against
    a durable 3-replica Raft notary cluster while a commit storm (with
    deliberate client re-submissions and double-spend attempts) runs.
    The run must end with every honest commit applied exactly once, every
    double-spend rejected, and bit-identical uniqueness state on all
    replicas — and the plan must actually have injected faults.

    The lock-order sanitizer (observability/lockwatch, ISSUE 6) is
    installed for the whole storm: every lock the cluster constructs is
    watched, and the run additionally asserts an EMPTY cycle report —
    chaos interleavings are exactly when an A→B/B→A inversion would
    surface."""

    def test_chaos_storm_converges_to_identical_state(self, tmp_path):
        from corda_tpu.observability import lockwatch

        # watch every lock the cluster is about to construct; the patch
        # must be UNDONE even when cluster setup itself raises, so the
        # whole storm (setup included) runs inside this try
        lockwatch.reset()
        lockwatch.install()
        try:
            self._storm(tmp_path)
        finally:
            lockwatch.uninstall()
            lockwatch.reset()

    def _storm(self, tmp_path):
        from corda_tpu.crypto import SecureHash
        from corda_tpu.faultinject import (
            ChaosOrchestrator,
            CrashEvent,
            FaultInjector,
            FaultPlan,
        )
        from corda_tpu.ledger import StateRef
        from corda_tpu.messaging import InMemoryMessagingNetwork
        from corda_tpu.notary import NotaryError, RaftUniquenessProvider
        from corda_tpu.observability import lockwatch

        def ref(n):
            return StateRef(SecureHash(n.to_bytes(2, "big") * 16), 0)

        def tx(n):
            return SecureHash((10_000 + n).to_bytes(2, "big") * 16)

        plan = FaultPlan(
            seed=2026, drop_p=0.08, delay_p=0.12, duplicate_p=0.1,
            crashes=(CrashEvent(at_round=500, node="s1", down_rounds=2500),),
        )
        inj = FaultInjector(plan)
        net = InMemoryMessagingNetwork(fault_injector=inj)
        orch = ChaosOrchestrator(net, inj)
        names = ["s0", "s1", "s2"]
        storage = str(tmp_path)
        providers = {
            n: RaftUniquenessProvider.make_node(n, names, net, storage)
            for n in names
        }
        for p in providers.values():
            p.node.start()

        def stop_s1():
            providers["s1"].close()
            net.stop_node("s1")

        def restart_s1():
            endpoint = net.restart_node("s1")
            providers["s1"] = RaftUniquenessProvider.make_node_on_endpoint(
                "s1", names, endpoint, storage_path=f"{storage}/s1.db",
                election_timeout_s=(0.15, 0.3), heartbeat_s=0.05,
            )
            providers["s1"].node.start()

        orch.register("s1", stop_s1, restart_s1)
        net.start_pumping()
        n_tx = 40
        try:
            def commit_retrying(provider, refs, tx_id):
                deadline = time.monotonic() + 60
                while True:
                    try:
                        provider.commit(refs, tx_id, "chaos-soak")
                        return None
                    except NotaryError as e:
                        if "already consumed" in str(e):
                            return e
                        if time.monotonic() > deadline:
                            raise
                    except (TimeoutError, FutureTimeoutError):
                        if time.monotonic() > deadline:
                            raise
                    time.sleep(0.05)

            for i in range(n_tx):
                assert commit_retrying(
                    providers["s0"], [ref(i)], tx(i)
                ) is None
                if i % 5 == 0:
                    # client retry of the SAME tx (lost-response replay):
                    # must return the original success, not double-spend
                    assert commit_retrying(
                        providers["s0"], [ref(i)], tx(i)
                    ) is None
                if i % 7 == 0:
                    # a DIFFERENT tx spending the same input must conflict
                    assert commit_retrying(
                        providers["s0"], [ref(i)], tx(1000 + i)
                    ) is not None
                time.sleep(0.01)

            # the scheduled crash must have fired during (or right after)
            # the storm; then wait out the restart
            deadline = time.monotonic() + 90
            while not any(e.kind == "crash" for e in inj.trace):
                assert time.monotonic() < deadline, "crash never fired"
                time.sleep(0.1)
            while "s1" in orch.down:
                assert time.monotonic() < deadline, "s1 never restarted"
                time.sleep(0.1)

            def rows(name):
                return sorted(
                    tuple(
                        bytes(c) if isinstance(c, (bytes, bytearray)) else c
                        for c in row
                    )
                    for row in providers[name].node._storage.dump_map()
                )

            deadline = time.monotonic() + 90
            while True:
                state = [rows(n) for n in names]
                if len(state[0]) == n_tx and state[0] == state[1] == state[2]:
                    break
                assert time.monotonic() < deadline, (
                    "replicas did not converge: "
                    f"{[len(s) for s in state]}"
                )
                time.sleep(0.25)
            # the plan actually exercised the cluster
            kinds = {e.kind for e in inj.trace}
            assert "crash" in kinds and "restart" in kinds
            assert kinds & {"drop", "delay", "duplicate"}
            # the lock-order sanitizer saw the whole storm: any A→B/B→A
            # inversion across the raft/messaging/flow locks is a
            # potential deadlock even though this run survived it
            report = lockwatch.cycle_report()
            assert report == [], (
                "lock-order inversions under chaos: "
                + "; ".join(" -> ".join(c["cycle"]) for c in report)
            )
        finally:
            for p in providers.values():
                try:
                    p.close()
                except Exception:
                    pass
            net.stop_pumping()
