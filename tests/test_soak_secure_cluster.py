"""Secure-fabric cluster soak (r3 VERDICT task 8).

One composed scenario over real processes: a 3-replica Raft notary
cluster and two dealers ride the mutually-authenticated fabric, a payment
storm runs against the cluster, and mid-storm a Raft replica AND an
out-of-process verifier worker are killed — the replica is then restarted
and must rejoin from its durable state. Asserts no lost commits (every
payment completes), no duplicate commits (balances reconcile exactly),
and throughput recovery (a post-restart wave completes like the first).

Reference shape: Disruption.kt (kill-the-node disruptions under loadtest)
+ VerifierTests.kt:55-113 (worker death redistributes work) + the
raft-notary demo's cluster.
"""

import time

import pytest

from corda_tpu.flows.api import class_path
from corda_tpu.ledger import CordaX500Name
from corda_tpu.testing import driver


@pytest.mark.slow
class TestSecureClusterSoak:
    def test_storm_survives_replica_and_worker_crash(self, tmp_path):
        from corda_tpu.finance import CashIssueFlow, CashPaymentFlow

        raft_names = [
            "O=Raft0,L=Zurich,C=CH",
            "O=Raft1,L=Zurich,C=CH",
            "O=Raft2,L=Zurich,C=CH",
        ]
        canon = [str(CordaX500Name.parse(n)) for n in raft_names]
        with driver(str(tmp_path), secure=True) as dsl:
            # Raft0 also serves the fabric + network map (driver harness
            # shape) — the replicas we crash are Raft1/Raft2
            notaries = [
                dsl.start_node(n, notary=True, raft_cluster=tuple(canon),
                               timeout_s=90)
                for n in raft_names
            ]
            alice = dsl.start_node(
                "O=Alice,L=London,C=GB", timeout_s=90,
                extra_config='verifierType = "OutOfProcess"',
            )
            bob = dsl.start_node("O=Bob,L=Rome,C=IT", timeout_s=90)
            worker1 = dsl.start_verifier_worker("soak-worker-1")
            worker2 = dsl.start_verifier_worker("soak-worker-2")

            conn = dsl.rpc(alice)
            bconn = dsl.rpc(bob)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                ids = conn.proxy.notary_identities()
                if (len(ids) >= 3
                        and len(conn.proxy.network_map_snapshot()) >= 5):
                    break
                time.sleep(0.3)
            ids = conn.proxy.notary_identities()
            assert len(ids) >= 3, f"cluster did not register: {ids}"
            # transactions name Raft0's identity (its process stays up)
            notary_party = next(
                p for p in ids if str(p.name) == canon[0]
            )
            bob_party = conn.proxy.well_known_party_from_x500_name(
                CordaX500Name.parse("O=Bob,L=Rome,C=IT")
            )

            per_wave, amount = 8, 10
            # one state per payment: concurrent payments racing on a
            # single big state would serialize on its soft lock (the
            # first locker's change only lands after its notarisation)
            issue_fids = [
                conn.proxy.start_flow_dynamic(
                    class_path(CashIssueFlow),
                    amount, "GBP", bytes([i]), notary_party,
                )
                for i in range(2 * per_wave)
            ]
            for f in issue_fids:
                conn.proxy.flow_result(f, 120)

            def wave(n):
                fids = [
                    conn.proxy.start_flow_dynamic(
                        class_path(CashPaymentFlow),
                        amount, "GBP", bob_party,
                    )
                    for _ in range(n)
                ]
                t0 = time.monotonic()
                for f in fids:
                    conn.proxy.flow_result(f, 240)
                return time.monotonic() - t0

            # ---- wave 1, with mid-wave crashes -------------------------
            fids = [
                conn.proxy.start_flow_dynamic(
                    class_path(CashPaymentFlow), amount, "GBP", bob_party
                )
                for _ in range(per_wave)
            ]
            time.sleep(1.5)  # let the storm reach the cluster
            notaries[2].kill()   # a Raft replica dies mid-window
            worker1.kill()       # a verifier worker dies mid-window
            for f in fids:       # no lost commits: every payment lands
                conn.proxy.flow_result(f, 240)

            # ---- restart the replica; it must rejoin from durable state
            restarted = dsl.start_node(
                raft_names[2], notary=True, raft_cluster=tuple(canon),
                timeout_s=90,
            )
            assert restarted.alive

            # ---- wave 2: throughput recovery ---------------------------
            wave2_s = wave(per_wave)
            assert wave2_s < 180, f"post-restart wave too slow: {wave2_s:.0f}s"

            # ---- no duplicate/lost commits: balances reconcile exactly -
            deadline = time.monotonic() + 60
            want = 2 * per_wave * amount
            while time.monotonic() < deadline:
                page = bconn.proxy.vault_query_by()
                got = sum(
                    sr.state.data.amount.quantity for sr in page.states
                )
                if got == want:
                    break
                time.sleep(0.5)
            assert got == want, f"bob holds {got}, expected {want}"
            apage = conn.proxy.vault_query_by()
            assert sum(
                sr.state.data.amount.quantity for sr in apage.states
            ) == 0, "alice kept cash that was spent"
