// Durable queue engine — the native runtime core of the messaging fabric.
//
// Role parity with the reference's embedded Apache Artemis broker
// (node/.../messaging/ArtemisMessagingServer.kt — a Java broker process
// doing durable store-and-forward with acks and redelivery). Re-designed
// as a small C++ engine with an append-only journal:
//
//   - named FIFO queues, competing consumers;
//   - publish is idempotent on msg_id (publisher dedupe — the processed-
//     message-table property of NodeMessagingClient.kt:187,429-439);
//   - consume leases a message for a visibility window; ack deletes,
//     expiry redelivers (at-least-once — VerifierTests.kt:75 elasticity);
//   - crash recovery by journal replay: pending = published − acked.
//
// Journal record format (little-endian):
//   [u8 kind][u32 body_len][body]
//   kind 1 = PUB: u16 qlen,q; u16 ilen,id; u16 slen,sender; u16 rlen,reply;
//                 u64 enqueued_us; u32 plen,payload
//   kind 2 = ACK: u16 ilen,id
//   kind 3 = DELIVERED (first lease): u16 ilen,id — so a crash-redelivered
//            message still reports redelivered=true after replay
//
// Exposed as a C ABI consumed by ctypes (corda_tpu/messaging/native_queue.py).

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <chrono>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <deque>
#include <unordered_set>
#include <vector>

#ifndef _WIN32
#include <unistd.h>
#include <sys/types.h>
#endif

namespace {

using Clock = std::chrono::steady_clock;

double now_s() {
    return std::chrono::duration<double>(Clock::now().time_since_epoch())
        .count();
}

uint64_t wall_us() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::system_clock::now().time_since_epoch())
        .count();
}

struct Pending {
    uint64_t seq;
    std::string queue, msg_id, sender, reply_to;
    std::string payload;
    uint64_t enqueued_us;
    double leased_until = 0.0;  // 0 = available
    int delivery_count = 0;
};

void put_u16(std::string& b, uint16_t v) { b.append((char*)&v, 2); }
void put_u32(std::string& b, uint32_t v) { b.append((char*)&v, 4); }
void put_u64(std::string& b, uint64_t v) { b.append((char*)&v, 8); }
void put_str16(std::string& b, const std::string& s) {
    put_u16(b, (uint16_t)s.size());
    b.append(s);
}

struct Reader {
    const char* p;
    const char* end;
    bool ok = true;
    template <typename T> T get() {
        if (p + sizeof(T) > end) { ok = false; return T{}; }
        T v;
        std::memcpy(&v, p, sizeof(T));
        p += sizeof(T);
        return v;
    }
    std::string str16() {
        uint16_t n = get<uint16_t>();
        if (!ok || p + n > end) { ok = false; return {}; }
        std::string s(p, n);
        p += n;
        return s;
    }
    std::string blob32() {
        uint32_t n = get<uint32_t>();
        if (!ok || p + n > end) { ok = false; return {}; }
        std::string s(p, n);
        p += n;
        return s;
    }
};

class Broker {
  public:
    Broker(const std::string& path, double visibility_s, bool fsync_each)
        : path_(path), visibility_s_(visibility_s), fsync_each_(fsync_each) {
        in_memory_ = path.empty() || path == ":memory:";
        if (!in_memory_) {
            // replay existing journal, then append. A crash can leave a
            // torn tail record; appending after it would make the NEXT
            // replay misparse everything that follows, so truncate to the
            // last well-formed record first.
            long good_end = 0;
            std::FILE* f = std::fopen(path.c_str(), "rb");
            if (f) {
                good_end = replay(f);
                std::fclose(f);
            }
#ifndef _WIN32
            if (good_end >= 0) {
                if (truncate(path.c_str(), good_end) != 0) {
                    // fall through: reopen append still works; worst case
                    // the torn tail persists and the next open retries
                }
            }
#endif
            log_ = std::fopen(path.c_str(), "ab");
            if (!log_) throw std::runtime_error("cannot open journal");
        }
    }

    ~Broker() {
        if (log_) std::fclose(log_);
    }

    bool publish(const std::string& queue, const std::string& msg_id,
                 const std::string& sender, const std::string& reply_to,
                 const std::string& payload) {
        std::unique_lock<std::mutex> lk(mu_);
        if (closed_ || failed_) return false;
        // dedupe: still-pending or recently-acked ids are silent no-ops
        if (by_id_.count(msg_id) || acked_set_.count(msg_id)) return true;
        auto msg = std::make_shared<Pending>();
        msg->seq = next_seq_++;
        msg->queue = queue;
        msg->msg_id = msg_id;
        msg->sender = sender;
        msg->reply_to = reply_to;
        msg->payload = payload;
        msg->enqueued_us = wall_us();
        by_id_[msg_id] = msg;
        queues_[queue][msg->seq] = msg;
        if (log_) {
            std::string body;
            put_str16(body, queue);
            put_str16(body, msg_id);
            put_str16(body, sender);
            put_str16(body, reply_to);
            put_u64(body, msg->enqueued_us);
            put_u32(body, (uint32_t)payload.size());
            body.append(payload);
            write_record(1, body);
        }
        cv_.notify_all();
        return true;
    }

    // Returns a malloc'd packed message or nullptr on timeout/closed.
    // Layout: u32 idlen,id; u32 slen,sender; u32 rlen,reply; u8 redelivered;
    //         u64 enqueued_us; u32 plen,payload
    char* consume(const std::string& queue, double timeout_s,
                  uint32_t* out_len) {
        std::unique_lock<std::mutex> lk(mu_);
        double deadline = timeout_s < 0 ? -1 : now_s() + timeout_s;
        while (true) {
            if (closed_) return nullptr;
            Pending* m = try_lease(queue);
            if (m) return pack(m, out_len);
            double now = now_s();
            if (deadline >= 0 && now >= deadline) return nullptr;
            double wait = 0.2;  // bounded: re-offer expired leases
            if (deadline >= 0 && deadline - now < wait) wait = deadline - now;
            cv_.wait_for(lk, std::chrono::duration<double>(wait));
        }
    }

    bool ack(const std::string& msg_id) {
        std::unique_lock<std::mutex> lk(mu_);
        if (failed_) return false;
        auto it = by_id_.find(msg_id);
        if (it == by_id_.end()) return false;
        auto msg = it->second;
        queues_[msg->queue].erase(msg->seq);
        by_id_.erase(it);
        acked_count_++;
        remember_acked(msg_id);
        if (log_) {
            std::string body;
            put_str16(body, msg_id);
            write_record(2, body);
        }
        cv_.notify_all();
        return true;
    }

    bool nack(const std::string& msg_id) {
        std::unique_lock<std::mutex> lk(mu_);
        auto it = by_id_.find(msg_id);
        if (it == by_id_.end()) return false;
        it->second->leased_until = 0.0;  // immediately re-deliverable
        cv_.notify_all();
        return true;
    }

    int64_t depth(const std::string& queue) {
        std::unique_lock<std::mutex> lk(mu_);
        auto it = queues_.find(queue);
        return it == queues_.end() ? 0 : (int64_t)it->second.size();
    }

    // newline-joined names of non-empty queues (malloc'd; caller frees)
    char* queue_list(uint32_t* out_len) {
        std::unique_lock<std::mutex> lk(mu_);
        std::string b;
        for (auto& [name, q] : queues_) {
            if (q.empty()) continue;
            if (!b.empty()) b.push_back('\n');
            b.append(name);
        }
        char* out = (char*)std::malloc(b.size() ? b.size() : 1);
        std::memcpy(out, b.data(), b.size());
        *out_len = (uint32_t)b.size();
        return out;
    }

    void close() {
        std::unique_lock<std::mutex> lk(mu_);
        closed_ = true;
        if (log_) {
            std::fflush(log_);
#ifndef _WIN32
            fsync(fileno(log_));
#endif
        }
        cv_.notify_all();
    }

  private:
    Pending* try_lease(const std::string& queue) {
        auto qit = queues_.find(queue);
        if (qit == queues_.end()) return nullptr;
        double now = now_s();
        for (auto& [seq, msg] : qit->second) {
            if (msg->leased_until <= now) {
                msg->leased_until = now + visibility_s_;
                msg->delivery_count++;
                if (msg->delivery_count == 1 && log_) {
                    // persist first delivery: after a crash the replayed
                    // message must redeliver flagged redelivered=true
                    std::string body;
                    put_str16(body, msg->msg_id);
                    write_record(3, body);
                }
                return msg.get();
            }
        }
        return nullptr;
    }

    static char* pack(const Pending* m, uint32_t* out_len) {
        std::string b;
        put_u32(b, (uint32_t)m->msg_id.size());
        b.append(m->msg_id);
        put_u32(b, (uint32_t)m->sender.size());
        b.append(m->sender);
        put_u32(b, (uint32_t)m->reply_to.size());
        b.append(m->reply_to);
        b.push_back(m->delivery_count > 1 ? 1 : 0);
        put_u64(b, m->enqueued_us);
        put_u32(b, (uint32_t)m->payload.size());
        b.append(m->payload);
        char* out = (char*)std::malloc(b.size());
        std::memcpy(out, b.data(), b.size());
        *out_len = (uint32_t)b.size();
        return out;
    }

    // Artemis-style bounded duplicate-ID cache: acked ids are remembered
    // FIFO up to a cap (pending ids dedupe via by_id_ regardless)
    static constexpr size_t kAckedCacheMax = 100000;
    void remember_acked(const std::string& id) {
        if (acked_set_.insert(id).second) {
            acked_fifo_.push_back(id);
            while (acked_fifo_.size() > kAckedCacheMax) {
                acked_set_.erase(acked_fifo_.front());
                acked_fifo_.pop_front();
            }
        }
    }

    void write_record(uint8_t kind, const std::string& body) {
        // a short write (disk full, I/O error) must NOT be reported as
        // durable success: flag the broker failed so publish/ack refuse
        // further work instead of silently diverging from the journal
        uint32_t len = (uint32_t)body.size();
        bool ok = std::fwrite(&kind, 1, 1, log_) == 1
            && std::fwrite(&len, 4, 1, log_) == 1
            && std::fwrite(body.data(), 1, body.size(), log_) == body.size()
            && std::fflush(log_) == 0;
        if (ok && fsync_each_) {
#ifndef _WIN32
            ok = fsync(fileno(log_)) == 0;
#endif
        }
        if (!ok) failed_ = true;
    }

    long replay(std::FILE* f) {
        std::vector<char> buf;
        long good_end = 0;
        while (true) {
            uint8_t kind;
            uint32_t len;
            if (std::fread(&kind, 1, 1, f) != 1) break;
            if (std::fread(&len, 4, 1, f) != 1) break;
            if (len > (64u << 20)) break;  // garbage length: stop at tear
            buf.resize(len);
            if (len && std::fread(buf.data(), 1, len, f) != len)
                break;  // torn tail record: truncated by the caller
            Reader r{buf.data(), buf.data() + len};
            if (kind == 1) {
                auto msg = std::make_shared<Pending>();
                msg->queue = r.str16();
                msg->msg_id = r.str16();
                msg->sender = r.str16();
                msg->reply_to = r.str16();
                msg->enqueued_us = r.get<uint64_t>();
                msg->payload = r.blob32();
                if (!r.ok) break;
                msg->seq = next_seq_++;
                by_id_[msg->msg_id] = msg;
                queues_[msg->queue][msg->seq] = msg;
            } else if (kind == 2) {
                std::string id = r.str16();
                if (!r.ok) break;
                auto it = by_id_.find(id);
                if (it != by_id_.end()) {
                    queues_[it->second->queue].erase(it->second->seq);
                    by_id_.erase(it);
                }
                remember_acked(id);
            } else if (kind == 3) {
                std::string id = r.str16();
                if (!r.ok) break;
                auto it = by_id_.find(id);
                if (it != by_id_.end()) it->second->delivery_count = 1;
            } else {
                break;  // unknown kind: stop at corruption
            }
            good_end = std::ftell(f);
        }
        return good_end;
    }

    std::string path_;
    double visibility_s_;
    bool fsync_each_;
    bool in_memory_ = false;
    bool closed_ = false;
    bool failed_ = false;
    std::FILE* log_ = nullptr;
    std::mutex mu_;
    std::condition_variable cv_;
    uint64_t next_seq_ = 1;
    uint64_t acked_count_ = 0;
    std::deque<std::string> acked_fifo_;
    std::unordered_set<std::string> acked_set_;
    std::unordered_map<std::string, std::shared_ptr<Pending>> by_id_;
    std::map<std::string, std::map<uint64_t, std::shared_ptr<Pending>>>
        queues_;
};

std::mutex g_reg_mu;
std::unordered_map<int64_t, std::shared_ptr<Broker>> g_brokers;
int64_t g_next_handle = 1;

}  // namespace

extern "C" {

int64_t ctq_open(const char* path, double visibility_s, int fsync_each) {
    try {
        auto broker = std::make_shared<Broker>(
            path ? path : "", visibility_s, fsync_each != 0);
        std::lock_guard<std::mutex> lk(g_reg_mu);
        int64_t h = g_next_handle++;
        g_brokers[h] = std::move(broker);
        return h;
    } catch (...) {
        return 0;
    }
}

// returns an owning reference: a concurrent ctq_close cannot free the
// broker out from under a blocked consume
static std::shared_ptr<Broker> get(int64_t h) {
    std::lock_guard<std::mutex> lk(g_reg_mu);
    auto it = g_brokers.find(h);
    return it == g_brokers.end() ? nullptr : it->second;
}

int ctq_publish(int64_t h, const char* queue, const char* msg_id,
                const char* sender, const char* reply_to,
                const char* payload, uint32_t payload_len) {
    auto b = get(h);
    if (!b) return 0;
    return b->publish(queue, msg_id, sender ? sender : "",
                      reply_to ? reply_to : "",
                      std::string(payload, payload_len))
               ? 1
               : 0;
}

char* ctq_consume(int64_t h, const char* queue, double timeout_s,
                  uint32_t* out_len) {
    auto b = get(h);
    if (!b) return nullptr;
    return b->consume(queue, timeout_s, out_len);
}

int ctq_ack(int64_t h, const char* msg_id) {
    auto b = get(h);
    return b && b->ack(msg_id) ? 1 : 0;
}

int ctq_nack(int64_t h, const char* msg_id) {
    auto b = get(h);
    return b && b->nack(msg_id) ? 1 : 0;
}

int64_t ctq_depth(int64_t h, const char* queue) {
    auto b = get(h);
    return b ? b->depth(queue) : -1;
}

char* ctq_queues(int64_t h, uint32_t* out_len) {
    auto b = get(h);
    if (!b) return nullptr;
    return b->queue_list(out_len);
}

void ctq_free(char* p) { std::free(p); }

void ctq_close(int64_t h) {
    auto b = get(h);
    if (b) b->close();
    std::lock_guard<std::mutex> lk(g_reg_mu);
    g_brokers.erase(h);
}

}  // extern "C"
