// Portable scalar ed25519 verification — the measured stand-in for the
// reference's CPU path.
//
// The reference verifies transaction signatures one at a time on the JVM
// through net.i2p.crypto.eddsa (Crypto.kt:621-624 via the EdDSA provider
// registered in Crypto.kt:115-137) — a pure-software, non-SIMD, scalar
// implementation. No JVM exists in this environment, so the north-star
// multiple ("N x the reference CPU path") is anchored to THIS library
// instead: a pure-software scalar engine (radix-2^25.5 field elements,
// schoolbook multiplication, a joint double-scalar bit ladder), compiled
// -O2 without vector intrinsics. The anchor is not claimed to dominate
// the Java engine — i2p uses ref10-style windowed/NAF scalar
// multiplication (fewer point ops than this ladder) while paying JVM
// overhead; BASELINE.md carries the robustness analysis for the
// north-star verdict under a generous allowance for that difference.
//
// Scope: the hot core only. The caller supplies h = SHA-512(R‖A‖M) mod L
// (hashing is <1% of a verify and would only pad the baseline); the
// library decompresses A, walks the 256-step joint ladder for
// [s]B + [h](−A), inverts, and compares the canonical encoding with R.
// Variable-time (branchy table picks) — verification is public data.

#include <cstdint>
#include <cstring>

typedef int32_t fe[10]; // radix 2^25.5: limb i has 26 bits (even) / 25 (odd)

static const int WIDTH[10] = {26, 25, 26, 25, 26, 25, 26, 25, 26, 25};

// 2p with every limb maxed: added before subtraction to keep limbs
// non-negative (value unchanged mod p)
static const int32_t TWO_P[10] = {
    0x7ffffda, 0x3fffffe, 0x7fffffe, 0x3fffffe, 0x7fffffe,
    0x3fffffe, 0x7fffffe, 0x3fffffe, 0x7fffffe, 0x3fffffe,
};

static void fe_copy(fe h, const fe f) { memcpy(h, f, sizeof(fe)); }

static void fe_zero(fe h) { memset(h, 0, sizeof(fe)); }

static void fe_one(fe h) { fe_zero(h); h[0] = 1; }

static void carry64(int64_t c[10], fe out) {
    // three passes settle any product column sum; the 2^255 wrap is *19
    for (int pass = 0; pass < 3; pass++) {
        for (int i = 0; i < 10; i++) {
            int64_t q = c[i] >> WIDTH[i];
            c[i] -= q << WIDTH[i];
            if (i == 9) c[0] += 19 * q; else c[i + 1] += q;
        }
    }
    for (int i = 0; i < 10; i++) out[i] = (int32_t)c[i];
}

static void fe_add(fe h, const fe f, const fe g) {
    int64_t c[10];
    for (int i = 0; i < 10; i++) c[i] = (int64_t)f[i] + g[i];
    carry64(c, h);
}

static void fe_sub(fe h, const fe f, const fe g) {
    int64_t c[10];
    for (int i = 0; i < 10; i++) c[i] = (int64_t)f[i] + TWO_P[i] - g[i];
    carry64(c, h);
}

static void fe_mul(fe h, const fe f, const fe g) {
    int64_t c[19];
    memset(c, 0, sizeof(c));
    for (int i = 0; i < 10; i++)
        for (int j = 0; j < 10; j++) {
            int64_t t = (int64_t)f[i] * g[j];
            // odd*odd limbs land one bit below their column's weight
            if ((i & 1) && (j & 1)) t *= 2;
            c[i + j] += t;
        }
    for (int k = 18; k >= 10; k--) c[k - 10] += 19 * c[k];
    carry64(c, h);
}

static void fe_sq(fe h, const fe f) { fe_mul(h, f, f); }

static void fe_mul_small(fe h, const fe f, int32_t n) {
    int64_t c[10];
    for (int i = 0; i < 10; i++) c[i] = (int64_t)f[i] * n;
    carry64(c, h);
}

// z^e for a fixed 255-bit exponent given as little-endian bits
static void fe_pow(fe out, const fe z, const uint8_t *bits, int nbits) {
    fe r, t;
    fe_one(r);
    for (int i = nbits - 1; i >= 0; i--) {
        fe_sq(t, r);
        fe_copy(r, t);
        if (bits[i]) {
            fe_mul(t, r, z);
            fe_copy(r, t);
        }
    }
    fe_copy(out, r);
}

static uint8_t P_MINUS_2_BITS[255];
static uint8_t P_PLUS_3_OVER_8_BITS[252];
static int exp_ready = 0;

static void init_exponents() {
    if (exp_ready) return;
    // p - 2 = 2^255 - 21: bits via big-endian subtraction done by hand —
    // p-2 = 0x7fff...ffeb
    uint8_t pm2[32];
    memset(pm2, 0xff, 32);
    pm2[0] = 0xeb;
    pm2[31] = 0x7f;
    for (int i = 0; i < 255; i++)
        P_MINUS_2_BITS[i] = (pm2[i >> 3] >> (i & 7)) & 1;
    // (p + 3) / 8 = 2^252 - 2
    uint8_t pe[32];
    memset(pe, 0xff, 32);
    pe[0] = 0xfe;
    pe[31] = 0x0f;
    for (int i = 0; i < 252; i++)
        P_PLUS_3_OVER_8_BITS[i] = (pe[i >> 3] >> (i & 7)) & 1;
    exp_ready = 1;
}

static void fe_invert(fe out, const fe z) { fe_pow(out, z, P_MINUS_2_BITS, 255); }

static void fe_frombytes(fe h, const uint8_t s[32]) {
    int64_t c[10];
    memset(c, 0, sizeof(c));
    int bit = 0;
    for (int i = 0; i < 10; i++) {
        int64_t v = 0;
        for (int b = 0; b < WIDTH[i] && bit < 255; b++, bit++) {
            v |= (int64_t)((s[bit >> 3] >> (bit & 7)) & 1) << b;
        }
        c[i] = v;
    }
    carry64(c, h);
}

static void fe_tobytes(uint8_t s[32], const fe f) {
    // canonical reduction: limbs are non-negative (< 2^26); estimate
    // q = floor(v / p) (0 or a few), fold q*19 into limb 0, carry with
    // masking and drop bit 255
    int64_t h[10];
    for (int i = 0; i < 10; i++) h[i] = f[i];
    int64_t q = (19 * h[9] + (((int64_t)1) << 24)) >> 25;
    for (int i = 0; i < 10; i++) q = (h[i] + q) >> WIDTH[i];
    h[0] += 19 * q;
    int64_t carry = 0;
    for (int i = 0; i < 10; i++) {
        h[i] += carry;
        carry = h[i] >> WIDTH[i];
        h[i] &= ((int64_t)1 << WIDTH[i]) - 1;
    }
    memset(s, 0, 32);
    int bit = 0;
    for (int i = 0; i < 10; i++)
        for (int b = 0; b < WIDTH[i] && bit < 255; b++, bit++)
            if ((h[i] >> b) & 1) s[bit >> 3] |= 1 << (bit & 7);
}

static int fe_isnegative(const fe f) {
    uint8_t s[32];
    fe_tobytes(s, f);
    return s[0] & 1;
}

static int fe_iszero(const fe f) {
    uint8_t s[32];
    fe_tobytes(s, f);
    for (int i = 0; i < 32; i++)
        if (s[i]) return 0;
    return 1;
}

// d and sqrt(-1) as byte constants (standard curve parameters)
static const uint8_t D_BYTES[32] = {
    0xa3, 0x78, 0x59, 0x13, 0xca, 0x4d, 0xeb, 0x75, 0xab, 0xd8, 0x41,
    0x41, 0x4d, 0x0a, 0x70, 0x00, 0x98, 0xe8, 0x79, 0x77, 0x79, 0x40,
    0xc7, 0x8c, 0x73, 0xfe, 0x6f, 0x2b, 0xee, 0x6c, 0x03, 0x52,
};
static const uint8_t SQRTM1_BYTES[32] = {
    0xb0, 0xa0, 0x0e, 0x4a, 0x27, 0x1b, 0xee, 0xc4, 0x78, 0xe4, 0x2f,
    0xad, 0x06, 0x18, 0x43, 0x2f, 0xa7, 0xd7, 0xfb, 0x3d, 0x99, 0x00,
    0x4d, 0x2b, 0x0b, 0xdf, 0xc1, 0x4f, 0x80, 0x24, 0x83, 0x2b,
};
// base point y = 4/5
static const uint8_t BY_BYTES[32] = {
    0x58, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
    0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66, 0x66,
};

struct ge { fe x, y, z, t; }; // extended twisted-Edwards

static void ge_identity(ge *p) {
    fe_zero(p->x); fe_one(p->y); fe_one(p->z); fe_zero(p->t);
}

static void ge_add(ge *r, const ge *p, const ge *q, const fe d2) {
    fe a, b, c, dd, e, f, g, h, t1, t2;
    fe_sub(t1, p->y, p->x);
    fe_sub(t2, q->y, q->x);
    fe_mul(a, t1, t2);
    fe_add(t1, p->y, p->x);
    fe_add(t2, q->y, q->x);
    fe_mul(b, t1, t2);
    fe_mul(t1, p->t, d2);
    fe_mul(c, t1, q->t);
    fe_mul(t1, p->z, q->z);
    fe_mul_small(dd, t1, 2);
    fe_sub(e, b, a);
    fe_sub(f, dd, c);
    fe_add(g, dd, c);
    fe_add(h, b, a);
    fe_mul(r->x, e, f);
    fe_mul(r->y, g, h);
    fe_mul(r->z, f, g);
    fe_mul(r->t, e, h);
}

static void ge_dbl(ge *r, const ge *p) {
    fe a, b, c, e, f, g, h, t1;
    fe_sq(a, p->x);
    fe_sq(b, p->y);
    fe_sq(t1, p->z);
    fe_mul_small(c, t1, 2);
    fe_add(h, a, b);
    fe_add(t1, p->x, p->y);
    fe_sq(t1, t1);
    fe_sub(e, h, t1);
    fe_sub(g, a, b);
    fe_add(f, c, g);
    fe_mul(r->x, e, f);
    fe_mul(r->y, g, h);
    fe_mul(r->z, f, g);
    fe_mul(r->t, e, h);
}

static void ge_neg(ge *r, const ge *p) {
    fe zero;
    fe_zero(zero);
    fe_sub(r->x, zero, p->x);
    fe_copy(r->y, p->y);
    fe_copy(r->z, p->z);
    fe_sub(r->t, zero, p->t);
}

// RFC 8032 decompression; returns 0 on failure
static int ge_frombytes(ge *p, const uint8_t s[32], const fe d) {
    fe u, v, v3, x2, m1, one, t;
    init_exponents();
    fe_frombytes(p->y, s);
    fe_one(one);
    fe_sq(u, p->y);
    fe_mul(v, u, d);
    fe_sub(u, u, one);   // y^2 - 1
    fe_add(v, v, one);   // d y^2 + 1
    // x = (u/v)^((p+3)/8) = u v^3 (u v^7)^((p-5)/8); use the pow-based
    // route: x = u v^3 * (u v^7)^((p-5)/8)  ==  (u/v)^((p+3)/8)
    fe_sq(t, v);
    fe_mul(v3, t, v);          // v^3
    fe_sq(t, v3);
    fe_mul(t, t, v);           // v^7
    fe_mul(t, t, u);           // u v^7
    // (p-5)/8 = (p+3)/8 - 1 → z^((p-5)/8) = z^((p+3)/8) / z
    fe x;
    fe_pow(x, t, P_PLUS_3_OVER_8_BITS, 252); // t^((p+3)/8)
    fe tinv;
    fe_invert(tinv, t);
    fe_mul(x, x, tinv);        // t^((p-5)/8)
    fe_mul(x, x, v3);
    fe_mul(x, x, u);           // u v^3 (u v^7)^((p-5)/8)
    fe_sq(x2, x);
    fe_mul(x2, x2, v);
    fe_sub(t, x2, u);
    if (!fe_iszero(t)) {
        fe_add(t, x2, u);
        if (!fe_iszero(t)) return 0;
        fe_frombytes(m1, SQRTM1_BYTES);
        fe_mul(x, x, m1);
    }
    if (fe_iszero(x) && (s[31] >> 7)) return 0;
    if (fe_isnegative(x) != (s[31] >> 7)) {
        fe zero;
        fe_zero(zero);
        fe_sub(x, zero, x);
    }
    fe_copy(p->x, x);
    fe_one(p->z);
    fe_mul(p->t, p->x, p->y);
    return 1;
}

static void ge_tobytes(uint8_t s[32], const ge *p) {
    fe zi, x, y;
    fe_invert(zi, p->z);
    fe_mul(x, p->x, zi);
    fe_mul(y, p->y, zi);
    fe_tobytes(s, y);
    s[31] ^= (uint8_t)(fe_isnegative(x) << 7);
}

extern "C" {

// Verify one signature given the reduced challenge h = SHA512(R‖A‖M) mod L.
// Returns 1 valid / 0 invalid. (s < L and encoding lengths are checked by
// the Python caller, as the JVM wrapper does before the engine call.)
static ge CACHED_B;
static fe CACHED_D, CACHED_D2;
static int b_ready = 0;

static int init_base() {
    // the engine caches the curve constants and base point, as the JVM
    // implementation's parameter spec does
    if (b_ready) return 1;
    init_exponents();
    fe_frombytes(CACHED_D, D_BYTES);
    fe_add(CACHED_D2, CACHED_D, CACHED_D);
    uint8_t by[32];
    memcpy(by, BY_BYTES, 32);
    if (!ge_frombytes(&CACHED_B, by, CACHED_D)) return 0;
    b_ready = 1;
    return 1;
}

int ed25519_verify_core(const uint8_t pk[32], const uint8_t rb[32],
                        const uint8_t sb[32], const uint8_t hb[32]) {
    if (!init_base()) return 0;
    fe d2;
    fe_copy(d2, CACHED_D2);

    ge A, negA, B, bmA, acc, tmp;
    B = CACHED_B;
    if (!ge_frombytes(&A, pk, CACHED_D)) return 0;
    ge_neg(&negA, &A);

    ge_add(&bmA, &B, &negA, d2);
    ge_identity(&acc);
    // joint MSB-first bit ladder: dbl then add {1: B, 2: -A, 3: B-A}
    for (int i = 255; i >= 0; i--) {
        ge_dbl(&tmp, &acc);
        acc = tmp;
        int s_bit = (sb[i >> 3] >> (i & 7)) & 1;
        int h_bit = (hb[i >> 3] >> (i & 7)) & 1;
        if (s_bit && h_bit) { ge_add(&tmp, &acc, &bmA, d2); acc = tmp; }
        else if (s_bit)     { ge_add(&tmp, &acc, &B, d2); acc = tmp; }
        else if (h_bit)     { ge_add(&tmp, &acc, &negA, d2); acc = tmp; }
    }
    uint8_t enc[32];
    ge_tobytes(enc, &acc);
    return memcmp(enc, rb, 32) == 0 ? 1 : 0;
}

// Sequential batch loop — the per-signature shape of the reference's
// TransactionWithSignatures.checkSignaturesAreValid.
int ed25519_verify_loop(const uint8_t *pks, const uint8_t *rs,
                        const uint8_t *ss, const uint8_t *hs, int n,
                        uint8_t *out) {
    int ok = 0;
    for (int i = 0; i < n; i++) {
        out[i] = (uint8_t)ed25519_verify_core(
            pks + 32 * i, rs + 32 * i, ss + 32 * i, hs + 32 * i);
        ok += out[i];
    }
    return ok;
}

} // extern "C"
