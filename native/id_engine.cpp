// Batched WireTransaction Merkle-id computation (host tier).
//
// The notary's receive-path integrity sweep recomputes every transaction's
// id from its serialized component bytes (reference: the id IS the Merkle
// root over the components, WireTransaction.kt:139-195 + MerkleTree.kt).
// The schedule per transaction (ledger/wire.py:13-17):
//
//   nonce(g, i)  = sha256(salt ‖ "CTNONCE" ‖ g le32 ‖ i le32)
//   leaf(g, i)   = sha256(nonce(g, i) ‖ component_bytes)
//   group_root g = Merkle root over pow2-zero-padded leaves
//                  (ZERO_HASH when the group is empty)
//   tx id        = Merkle root over the pow2-zero-padded group roots
//
// Python hashlib pays ~5-8 µs of interpreter overhead per digest, which
// at ~30 digests per transaction caps the id stage near 7k tx/s; this
// engine runs the whole schedule in C++ (~1 µs/digest), keeping the
// Python side to one flattened-buffer hand-off. ctypes-bound via
// corda_tpu/native_build.py (same seam as queue_engine.cpp).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// ------------------------------------------------------- portable SHA-256
struct Sha256 {
    uint32_t h[8];
    uint8_t buf[64];
    uint64_t len = 0;
    size_t fill = 0;

    Sha256() {
        static const uint32_t init[8] = {
            0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
            0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u,
        };
        std::memcpy(h, init, sizeof h);
    }

    static uint32_t rotr(uint32_t x, int n) {
        return (x >> n) | (x << (32 - n));
    }

    void block(const uint8_t* p) {
        static const uint32_t K[64] = {
            0x428a2f98u,0x71374491u,0xb5c0fbcfu,0xe9b5dba5u,0x3956c25bu,
            0x59f111f1u,0x923f82a4u,0xab1c5ed5u,0xd807aa98u,0x12835b01u,
            0x243185beu,0x550c7dc3u,0x72be5d74u,0x80deb1feu,0x9bdc06a7u,
            0xc19bf174u,0xe49b69c1u,0xefbe4786u,0x0fc19dc6u,0x240ca1ccu,
            0x2de92c6fu,0x4a7484aau,0x5cb0a9dcu,0x76f988dau,0x983e5152u,
            0xa831c66du,0xb00327c8u,0xbf597fc7u,0xc6e00bf3u,0xd5a79147u,
            0x06ca6351u,0x14292967u,0x27b70a85u,0x2e1b2138u,0x4d2c6dfcu,
            0x53380d13u,0x650a7354u,0x766a0abbu,0x81c2c92eu,0x92722c85u,
            0xa2bfe8a1u,0xa81a664bu,0xc24b8b70u,0xc76c51a3u,0xd192e819u,
            0xd6990624u,0xf40e3585u,0x106aa070u,0x19a4c116u,0x1e376c08u,
            0x2748774cu,0x34b0bcb5u,0x391c0cb3u,0x4ed8aa4au,0x5b9cca4fu,
            0x682e6ff3u,0x748f82eeu,0x78a5636fu,0x84c87814u,0x8cc70208u,
            0x90befffau,0xa4506cebu,0xbef9a3f7u,0xc67178f2u,
        };
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16)
                 | (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18)
                        ^ (w[i - 15] >> 3);
            uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19)
                        ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
        uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + K[i] + w[i];
            uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const uint8_t* p, size_t n) {
        len += n;
        if (fill) {
            size_t take = 64 - fill;
            if (take > n) take = n;
            std::memcpy(buf + fill, p, take);
            fill += take; p += take; n -= take;
            if (fill == 64) { block(buf); fill = 0; }
        }
        while (n >= 64) { block(p); p += 64; n -= 64; }
        if (n) { std::memcpy(buf + fill, p, n); fill += n; }
    }

    void final(uint8_t out[32]) {
        uint64_t bits = len * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t zero = 0;
        while (fill != 56) update(&zero, 1);
        uint8_t lenb[8];
        for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8 * i));
        update(lenb, 8);
        for (int i = 0; i < 8; i++) {
            out[4 * i] = uint8_t(h[i] >> 24);
            out[4 * i + 1] = uint8_t(h[i] >> 16);
            out[4 * i + 2] = uint8_t(h[i] >> 8);
            out[4 * i + 3] = uint8_t(h[i]);
        }
    }
};

void sha256_once(const uint8_t* p, size_t n, uint8_t out[32]) {
    Sha256 s; s.update(p, n); s.final(out);
}

// Merkle root over a row of 32-byte digests, zero-padded to a power of two
// (MerkleTree.build, crypto/merkle.py:52-57). Operates in place.
void merkle_root(std::vector<uint8_t>& row, size_t n, uint8_t out[32]) {
    size_t p2 = 1;
    while (p2 < n) p2 <<= 1;
    row.resize(p2 * 32, 0);  // ZERO_HASH padding
    uint8_t pair[64];
    while (p2 > 1) {
        for (size_t i = 0; i < p2; i += 2) {
            std::memcpy(pair, row.data() + i * 32, 64);
            sha256_once(pair, 64, row.data() + (i / 2) * 32);
        }
        p2 >>= 1;
    }
    std::memcpy(out, row.data(), 32);
}

}  // namespace

extern "C" {

// Compute n_tx transaction ids.
//   salts:        n_tx × 32 bytes (privacy salts)
//   comp_data:    all component bytes, concatenated in (tx, group, index)
//                 flatten order
//   comp_len:     one length per component, same order
//   group_counts: n_tx × n_groups component counts (flatten order)
//   out_ids:      n_tx × 32 bytes
int corda_compute_tx_ids(
    const uint8_t* salts,
    const uint8_t* comp_data,
    const int32_t* comp_len,
    const int32_t* group_counts,
    int32_t n_tx,
    int32_t n_groups,
    uint8_t* out_ids)
{
    const uint8_t* cursor = comp_data;
    const int32_t* counts = group_counts;
    std::vector<uint8_t> leaves, groups, msg;
    for (int32_t t = 0; t < n_tx; t++) {
        const uint8_t* salt = salts + size_t(t) * 32;
        groups.assign(size_t(n_groups) * 32, 0);
        int comp_cursor = 0;
        for (int32_t g = 0; g < n_groups; g++) {
            int32_t n = counts[g];
            if (n < 0) return -1;
            if (n == 0) continue;  // empty group -> ZERO_HASH row
            leaves.assign(size_t(n) * 32, 0);
            for (int32_t i = 0; i < n; i++) {
                // nonce = sha256(salt ‖ "CTNONCE" ‖ g le32 ‖ i le32)
                uint8_t nonce[32];
                uint8_t hdr[32 + 7 + 8];
                std::memcpy(hdr, salt, 32);
                std::memcpy(hdr + 32, "CTNONCE", 7);
                for (int b = 0; b < 4; b++) {
                    hdr[39 + b] = uint8_t(uint32_t(g) >> (8 * b));
                    hdr[43 + b] = uint8_t(uint32_t(i) >> (8 * b));
                }
                sha256_once(hdr, sizeof hdr, nonce);
                // leaf = sha256(nonce ‖ component)
                int32_t clen = comp_len[comp_cursor];
                if (clen < 0) return -2;
                msg.resize(32 + size_t(clen));
                std::memcpy(msg.data(), nonce, 32);
                std::memcpy(msg.data() + 32, cursor, size_t(clen));
                sha256_once(msg.data(), msg.size(),
                            leaves.data() + size_t(i) * 32);
                cursor += clen;
                comp_cursor += 1;
            }
            merkle_root(leaves, size_t(n), groups.data() + size_t(g) * 32);
        }
        merkle_root(groups, size_t(n_groups),
                    out_ids + size_t(t) * 32);
        counts += n_groups;
        comp_len += comp_cursor;
    }
    return 0;
}

}  // extern "C"
