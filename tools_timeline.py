"""Telemetry-timeline renderer CLI (docs/OBSERVABILITY.md §Telemetry
timeline).

Renders a timeline ring snapshot — the fixed-cadence counter-delta /
timer-quantile / device-gauge / SLO-burn series the off-by-default
``TimelineRecorder`` samples — as an ASCII sparkline table: one row per
series with its kind, min/max/last values and the ring's shape over
time, plus any stamped marks (loadgen qps steps etc.). Reads from any
of the three places a timeline lands:

    python tools_timeline.py --flight FLIGHT.jsonl   # flight dump kind
    python tools_timeline.py --snapshot SNAP.json    # saved snapshot
    python tools_timeline.py --live                  # in-process demo

``--snapshot`` accepts a raw ``TimelineRecorder.snapshot()`` dict (what
``CordaRPCOps.timeline_snapshot()`` returns — pipe a remote scrape to a
file and point this at it), or any JSON carrying one under a
``timeline`` key (a ``monitoring_snapshot()``, a ``bench.py --smoke``
artifact). ``--live`` forces the timeline on around a host-path
scheduler burst and renders what the rings caught — a seconds-fast
demo of the recorder end to end.

Concurrency-observatory series (``contention.*`` counter deltas and
wait-time quantile rings — docs/OBSERVABILITY.md §Concurrency
observatory) group under their own subheading in the sparkline table,
and when the artifact also carries a ``contention`` section (a flight
dump's kind, a monitoring snapshot's key) the top-contended table and
wait edges print beneath it.

Knobs:

    --flight PATH    render the ``timeline`` kind of a flight dump
    --snapshot PATH  render a snapshot JSON (raw or nested)
    --live           in-process demo burst (no artifact needed)
    --points N       show only the last N ring points (default: all)
    --width N        sparkline glyph budget per row (default 32)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

ROOT = Path(__file__).parent
sys.path.insert(0, str(ROOT))

_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: list, width: int = 32) -> str:
    """Min-max-normalised sparkline of ``values``; flat series render as
    all-low so a spike is always visible against its floor."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * len(_SPARK)))]
        for v in vals
    )


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e9:
        return str(int(v))
    return f"{v:.4g}"


def extract_timeline(doc: dict) -> dict | None:
    """Find the timeline snapshot inside ``doc``: the dict itself when it
    IS a snapshot (has ``series``), else its ``timeline`` key."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("series"), dict):
        return doc
    inner = doc.get("timeline")
    if isinstance(inner, dict) and isinstance(inner.get("series"), dict):
        return inner
    return None


def render_timeline(snap: dict, *, points: int | None = None,
                    width: int = 32) -> str:
    """The sparkline table for one snapshot, as a printable string."""
    ts = list(snap.get("timestamps") or [])
    series = snap.get("series") or {}
    lines = []
    span = ts[-1] - ts[0] if len(ts) >= 2 else 0.0
    lines.append(
        f"timeline: {snap.get('ticks', len(ts))} ticks"
        f" @ {snap.get('cadence_s', '?')}s cadence,"
        f" {len(series)} series, {span:.2f}s span,"
        f" ring={snap.get('ring_points', '?')}"
    )
    if not series:
        lines.append("  (no series recorded)")
        return "\n".join(lines)
    name_w = max(len(n) for n in series) + 2
    kind_w = max(len(s.get("kind", "?")) for s in series.values()) + 2
    lines.append(
        f"  {'series'.ljust(name_w)}{'kind'.ljust(kind_w)}"
        f"{'min'.rjust(12)}{'max'.rjust(12)}{'last'.rjust(12)}  spark"
    )

    def row(name: str) -> str | None:
        s = series[name]
        pts = [float(v) for v in (s.get("points") or [])]
        if points is not None:
            pts = pts[-points:]
        if not pts:
            return None
        return (
            f"  {name.ljust(name_w)}{s.get('kind', '?').ljust(kind_w)}"
            f"{_fmt(min(pts)).rjust(12)}{_fmt(max(pts)).rjust(12)}"
            f"{_fmt(pts[-1]).rjust(12)}  {_sparkline(pts, width)}"
        )

    # the concurrency observatory's families (contention.* counter
    # deltas + wait-time quantile rings) group under their own
    # subheading so lock behaviour reads as one block next to the
    # PR 18 series rather than interleaving with them
    general = [n for n in sorted(series) if not n.startswith("contention.")]
    observatory = [n for n in sorted(series) if n.startswith("contention.")]
    for name in general:
        r = row(name)
        if r is not None:
            lines.append(r)
    rows = [r for r in (row(n) for n in observatory) if r is not None]
    if rows:
        lines.append("  contention (concurrency observatory):")
        lines.extend(rows)
    marks = snap.get("marks") or []
    if marks:
        lines.append(f"  marks ({len(marks)}):")
        for mk in marks:
            lines.append(
                f"    t={_fmt(float(mk.get('t', 0.0)))}"
                f" {mk.get('name', '?')}={_fmt(float(mk.get('value', 0.0)))}"
            )
    return "\n".join(lines)


def render_contention(section: dict, *, top_n: int = 8) -> str | None:
    """The top-contended table + wait edges from a ``contention``
    section (a flight dump's kind, or ``monitoring_snapshot()``'s key),
    as a printable string — None when the section is absent/disabled or
    carries no sites."""
    if not isinstance(section, dict) or not section.get("enabled"):
        return None
    top = section.get("top") or []
    if not top:
        return None
    lines = [f"contention: {len(section.get('sites') or {})} sites, "
             f"top {min(top_n, len(top))} by total wait:"]
    name_w = max(len(str(r.get("site", "?"))) for r in top[:top_n]) + 2
    lines.append(
        f"  {'site'.ljust(name_w)}{'acquires'.rjust(10)}"
        f"{'contended'.rjust(11)}{'wait_total'.rjust(12)}"
        f"{'wait_p95'.rjust(11)}{'hold_p95'.rjust(11)}"
    )
    for r in top[:top_n]:
        lines.append(
            f"  {str(r.get('site', '?')).ljust(name_w)}"
            f"{_fmt(float(r.get('acquires', 0))).rjust(10)}"
            f"{_fmt(float(r.get('contended', 0))).rjust(11)}"
            f"{float(r.get('wait_total_s', 0.0)):>11.4f}s"
            f"{float(r.get('wait_p95_s', 0.0)):>10.4f}s"
            f"{float(r.get('hold_p95_s', 0.0)):>10.4f}s"
        )
    edges = section.get("edges") or []
    if edges:
        lines.append(f"  wait edges ({len(edges)}):")
        for e in edges[:top_n]:
            lines.append(
                f"    {e.get('holder', '?')} -> {e.get('waiter', '?')}"
                f"  x{_fmt(float(e.get('count', 0)))}"
                f"  {float(e.get('wait_s', 0.0)):.4f}s"
            )
    return "\n".join(lines)


def run_live_demo() -> dict:
    """Force the timeline on around a host-path scheduler burst and
    return the snapshot — what a live ``CordaRPCOps.timeline_snapshot()``
    scrape of a loaded node looks like, without needing a node."""
    from corda_tpu.crypto import generate_keypair, sign
    from corda_tpu.observability import configure_timeline
    from corda_tpu.observability.timeseries import timeline
    from corda_tpu.serving import DeviceScheduler

    configure_timeline(enabled=True, cadence_s=0.05, ring_points=64,
                       thread=False, reset=True)
    tl = timeline()
    try:
        sched = DeviceScheduler(use_device_default=False)
        kp = generate_keypair()
        rows = []
        for i in range(8):
            msg = b"timeline-demo-%d" % i
            rows.append((kp.public, sign(kp.private, msg), msg))
        tl.tick()
        for step, reps in enumerate((1, 2, 4)):
            tl.mark("demo.step", float(reps))
            for _ in range(reps):
                sched.submit_rows(rows, use_device=False).result(timeout=60)
            tl.tick()
        sched.shutdown()
        return tl.snapshot()
    finally:
        configure_timeline(enabled=False, reset=True)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--flight", help="flight-dump JSONL to read")
    src.add_argument("--snapshot", help="snapshot JSON to read")
    src.add_argument("--live", action="store_true",
                     help="in-process demo burst")
    ap.add_argument("--points", type=int, default=None,
                    help="show only the last N ring points")
    ap.add_argument("--width", type=int, default=32,
                    help="sparkline glyph budget (default 32)")
    args = ap.parse_args(argv)

    contention_doc = None
    if args.live:
        snap = run_live_demo()
    elif args.flight:
        from corda_tpu.observability import read_flight_dump

        dump = read_flight_dump(args.flight)
        snap = dump.get("timeline")
        contention_doc = dump.get("contention")
        if not isinstance(snap, dict) or not snap.get("enabled"):
            print(f"timeline: no timeline kind in {args.flight} "
                  "(was the recorder enabled when the dump was written?)",
                  file=sys.stderr)
            return 1
    else:
        with open(args.snapshot, encoding="utf-8") as f:
            doc = json.load(f)
        snap = extract_timeline(doc)
        contention_doc = doc.get("contention") \
            if isinstance(doc, dict) else None
        if snap is None:
            print(f"timeline: no timeline snapshot in {args.snapshot}",
                  file=sys.stderr)
            return 1
    print(render_timeline(snap, points=args.points, width=args.width))
    # when the artifact also carries a contention section (a flight
    # dump's kind, a monitoring snapshot's key), append the
    # top-contended table under the sparklines
    table = render_contention(contention_doc)
    if table is not None:
        print(table)
    return 0


if __name__ == "__main__":
    sys.exit(main())
