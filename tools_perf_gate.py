"""Continuous perf-regression gate: bench JSON vs PERF_BASELINE.json.

The BENCH_*.json trajectory records every capture, but until this tool
nothing GATED on it — a PR could halve ``ed25519_sigs_per_sec`` and only
a human reading the numbers would notice. This gate compares one bench
result (``bench.py``'s JSON line, ``bench.py --smoke``'s JSON line, or a
saved ``BENCH_LOCAL.json``) against a checked-in baseline with
per-metric RELATIVE tolerances, and exits nonzero on any regression
beyond tolerance.

No device is needed for any mode: the gate is pure JSON arithmetic, so
it runs in tier-1 CI against a synthetic result, and on real hardware
against a fresh capture.

Modes::

    python tools_perf_gate.py --result BENCH_LOCAL.json          # gate (rc 0/1)
    python tools_perf_gate.py --result out.json --write-baseline # (re)base
    python tools_perf_gate.py --result out.json --check-schema   # shape only
    python tools_perf_gate.py --result out.json --history        # append entry
    python tools_perf_gate.py --trend                            # trajectory

**Perf-history sentinel** (``--history`` / ``--trend``): every gated
capture appends one line to ``BENCH_HISTORY.jsonl`` — wall time, ISO
date, short git rev, chip-vs-deviceless provenance, and every gated
metric present — so the bench trajectory is a first-class artifact
instead of a pile of orphan ``BENCH_r0x.json`` files. ``--trend``
renders each metric's recent trajectory and FAILS on a strict monotone
regression across the last K entries (``--trend-window``, default 3):
one noisy capture never trips it, K successive worsenings always do.
``bench.py`` appends a history entry automatically after every full run.

``--baseline`` overrides the baseline path (default: PERF_BASELINE.json
beside this file). ``--write-baseline`` records every known gated metric
present in the result, with the default tolerance table below (edit the
JSON to tighten/loosen per metric — the file, not this table, is the
contract once written).

Baseline schema (``PERF_BASELINE.json``)::

    {
      "schema": 1,
      "source": "<result file the baseline was generated from>",
      "metrics": {
        "<path>": {"baseline": <number>,
                    "rel_tol": <fraction>,
                    "direction": "higher" | "lower"}
      }
    }

Metric paths address the result JSON with ``/`` separators (profiler
kernel names contain dots): ``ed25519_sigs_per_sec`` is a top-level key,
``profile/ed25519.verify/rows_per_sec`` walks the per-stage profile
section bench.py emits. A ``higher`` metric fails when
``value < baseline * (1 - rel_tol)``; a ``lower`` metric (latencies)
fails when ``value > baseline * (1 + rel_tol)``. Metrics missing from
the result are reported but do NOT fail the gate (bench sections degrade
independently — a dead device must not read as a regression); a result
that is missing EVERY gated metric fails, since that gates nothing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

BASELINE_DEFAULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "PERF_BASELINE.json"
)

HISTORY_DEFAULT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl"
)

# Known gated metrics: path -> (direction, default relative tolerance).
# Device sig rates gate tight (they are the north-star axis and the chip
# is dedicated); end-to-end rates looser (they fold host scheduling
# noise); wall-clock latencies loosest (shared-host CI jitter).
GATED_METRICS: dict[str, tuple[str, float]] = {
    # full bench (BENCH_LOCAL.json / bench.py main JSON)
    "ed25519_sigs_per_sec": ("higher", 0.15),
    "ed25519_best_sigs_per_sec": ("higher", 0.15),
    "ecdsa_sigs_per_sec": ("higher", 0.15),
    "mixed_scheme_sigs_per_sec": ("higher", 0.25),
    # host-relative ratios: the "device beats host" acceptance axes for
    # the DAG-resolve and mixed-scheme pipelines — gating the RATIO means
    # a host-baseline speedup cannot mask a device-path regression. Tight
    # tolerances on purpose: the checked-in baseline tracks the last
    # committed chip capture, and every on-chip improvement should be
    # locked in promptly with --write-baseline (the baseline, not this
    # table, is the contract once written).
    "dag_vs_host": ("higher", 0.10),
    "mixed_vs_host": ("higher", 0.15),
    "value": ("higher", 0.20),                    # notarised tx/sec headline
    "notary_best_tx_per_sec": ("higher", 0.20),
    "notary_loadtest_tx_per_sec": ("higher", 0.30),
    "notary_raft_cluster_tx_per_sec": ("higher", 0.30),
    "notary_bft_cluster_tx_per_sec": ("higher", 0.30),
    "dag_1k_chain_tx_per_sec": ("higher", 0.25),
    "trader_demo_trades_per_sec": ("higher", 0.30),
    "empty_flows_per_sec": ("higher", 0.35),
    # smoke (bench.py --smoke JSON)
    "idle_dispatch_ms": ("lower", 1.00),
    "notary_ms": ("lower", 1.00),
    "total_s": ("lower", 1.00),
    # per-stage profile section (both modes): achieved steady-state rates
    "profile/ed25519.verify/rows_per_sec": ("higher", 0.50),
    "profile/ecdsa.verify/rows_per_sec": ("higher", 0.50),
    "profile/txid/rows_per_sec": ("higher", 0.50),
    "profile/sha256/rows_per_sec": ("higher", 0.50),
    # MFU: achieved VPU utilization per signature kernel (bench.py's mfu
    # section, ops-per-verify derived from the live kernel parameters by
    # corda_tpu/ops/opcount.py). First-class gated so an arithmetic
    # regression (or a model/tier mismatch) fails CI, not a human read.
    "mfu/ed25519/utilization_pct": ("higher", 0.25),
    "mfu/ecdsa/utilization_pct": ("higher", 0.25),
    # RLC batch-verify op model (docs/BATCH_VERIFY.md): amortized field
    # muls+sqs per signature at the model's batch size, straight from
    # corda_tpu/ops/opcount.py — fully deterministic (no device, no
    # timer), so the tolerance is only rounding slack. Lower is better;
    # a regression here means someone made the MSM do more work per row.
    "mfu/ed25519_batch/ops_per_verify": ("lower", 0.02),
    # mesh scheduling (docs/SERVING.md §Mesh scheduling): placement
    # balance over the stripe — rows_total / (n_devices × the busiest
    # ordinal's rows). Deterministic (no wall clock), 1.0 iff placement
    # spread the storm evenly, and the quantity wall-clock scaling on a
    # real multi-chip mesh is bounded by. Tight tolerance: imbalance is
    # a scheduler bug, not timer noise.
    "multichip/scaling_efficiency": ("higher", 0.05),
    # open-loop SLO attainment (docs/LOAD_HARNESS.md): the knee — the
    # highest Poisson arrival rate whose step met the SLO. Loose
    # tolerance: the smoke's knee rides mocknet flow latency on a shared
    # CI host. Two paths for the two artifacts: the smoke JSON nests a
    # ``loadtest`` section; a standalone LOADTEST.json (tools_loadgen.py)
    # IS the section, with ``knee_qps`` at top level.
    "loadtest/knee_qps": ("higher", 0.50),
    "knee_qps": ("higher", 0.50),
    # overload certification (docs/OVERLOAD.md): goodput retained past the
    # knee during a partition/chaos storm, and goodput recovered after the
    # storm ends, both relative to the pre-storm baseline. Loose tolerances:
    # both ratios ride mocknet latency under injected chaos on a shared CI
    # host — the hard floors (0.5 / 0.9) are enforced by the scenario's own
    # *_ok flags, which --check-schema requires to be true.
    "overload/goodput_ratio": ("higher", 0.40),
    "overload/recovery_ratio": ("higher", 0.30),
    # device-resident state store (docs/STATE_STORE.md): batched
    # membership-probe throughput against the sharded HBM table at low
    # occupancy. Loose tolerance: the smoke rides host-platform XLA on a
    # shared CI host; correctness (verdict/digest parity, spill
    # accounting) is enforced by the *_parity flags --check-schema pins.
    "statestore/probes_per_sec": ("higher", 0.50),
}

# keys every per-kernel profile entry must carry for --check-schema
PROFILE_REQUIRED_KEYS = (
    "compile_s", "execute_total_s", "batch_efficiency",
)

# keys every per-ordinal devices entry must carry for --check-schema
# (the per-device telemetry table bench.py --smoke emits — devicemon)
DEVICES_REQUIRED_KEYS = (
    "dispatches", "settles", "rows", "padded_rows",
)

# keys the smoke's resilience section must carry for --check-schema
# (the self-healing serving plane pass — docs/SERVING.md)
RESILIENCE_REQUIRED_KEYS = (
    "hedge_fired", "hedge_won_host", "hedge_won_device",
    "quarantine_entered", "quarantine_readmitted", "breaker_state",
)

# keys the smoke's durability section must carry for --check-schema
# (the crash-consistent persistence pass — docs/DURABILITY.md):
# recovery wall, the group-commit fsync quantiles, and the replayed /
# torn record counts of the recovery the pass performed
DURABILITY_REQUIRED_KEYS = (
    "recovery_wall_s", "wal_fsync_p50_ms", "wal_fsync_p99_ms",
    "replayed_records", "torn_records", "snapshot_records",
)

# keys the smoke's batchverify section must carry for --check-schema
# (the algebraic batch-verification pass — docs/BATCH_VERIFY.md):
# RLC batch≡per-sig parity, offender bisection, BLS aggregate round-trip
BATCHVERIFY_REQUIRED_KEYS = (
    "rlc_parity_ok", "rlc_rows", "offenders_expected", "offenders_found",
    "bls_aggregate_ok", "bls_signers",
)

# keys the smoke's multichip section must carry for --check-schema
# (the mesh-striped scheduler pass — docs/SERVING.md §Mesh scheduling):
# stripe coverage, load-balance scaling efficiency, whole-stripe
# mega-batch fusion and the consumed-set all-gather parity flags
MULTICHIP_REQUIRED_KEYS = (
    "n_devices", "ordinals_hit", "dispatches", "rows",
    "max_ordinal_rows", "scaling_efficiency", "stripe_spread_max",
    "megabatch_rows", "allgather_parity_ok", "mega_parity_ok",
)

# keys every loadtest step must carry for --check-schema (the open-loop
# SLO-attainment pass — docs/LOAD_HARNESS.md). The last two ride the
# per-edge network telemetry (messaging/netstats): harness runs with the
# toggle off still emit them as 0 / 0.0 — numeric, never absent.
LOADTEST_STEP_REQUIRED_KEYS = (
    "qps", "offered", "completed", "errors", "shed", "p50_s", "p99_s",
    "retransmits", "net_transit_p99_s",
)

# keys the smoke's cluster section must carry for --check-schema
# (the cluster-observatory pass — docs/OBSERVABILITY.md §Cluster
# observatory): assembled-trace hop census, transit quantiles, and the
# federation rollup + reconciliation flag
CLUSTER_REQUIRED_KEYS = (
    "hops", "nodes", "transit_p50_s", "transit_p99_s",
    "federation_nodes", "rollup_p99_s", "node_p99_min_s",
    "node_p99_max_s", "pernode_reconcile_ok",
)

# keys the overload section must carry for --check-schema (the
# metastability-certification pass — docs/OVERLOAD.md): offered load vs
# the knee, goodput retained during the storm and recovered after it,
# brownout ordering, and the retry-budget counter reconciliation
OVERLOAD_REQUIRED_KEYS = (
    "base_qps", "overload_qps", "deadline_s",
    "baseline_goodput_qps", "storm_goodput_qps", "goodput_ratio",
    "goodput_floor", "goodput_floor_ok",
    "recovery_goodput_qps", "recovery_ratio", "recovery_floor",
    "recovery_wall_s", "recovery_wall_limit_s", "recovery_ok",
    "brownout_order_ok", "admission_rejected", "deadline_shed",
    "retransmits", "retry_budget_granted", "retry_budget_denied",
    "retry_budget_earned", "retry_budget_ok",
)

# keys the smoke's statestore section must carry for --check-schema
# (the device-resident sharded state-store pass — docs/STATE_STORE.md):
# table shape, occupancy at the two load points, probe throughput, spill
# accounting and the verdict/digest oracle-parity flags
STATESTORE_REQUIRED_KEYS = (
    "rows", "shards", "slots_per_shard",
    "occupancy_low", "occupancy_high",
    "probes_per_sec", "probes_per_sec_high",
    "spill_rows", "verdict_parity", "digest_parity",
)

# keys the smoke's timeline section must carry for --check-schema (the
# telemetry-timeline pass — docs/OBSERVABILITY.md §Telemetry timeline):
# sampling cadence + tick census, the series breakdown, and the two
# acceptance flags (a synthetic burn-rate alert fired; the flight dump's
# timeline kind round-tripped)
TIMELINE_REQUIRED_KEYS = (
    "cadence_s", "ticks", "series", "counter_series", "timer_series",
    "burn_alerts", "flight_roundtrip_ok",
)

# keys every per-site entry in the smoke's contention section must carry
# for --check-schema (the concurrency-observatory pass —
# docs/OBSERVABILITY.md §Concurrency observatory): acquire/contention
# census plus both reservoir quantile triples
CONTENTION_SITE_REQUIRED_KEYS = (
    "acquires", "contended", "wait_total_s",
    "wait_p50_s", "wait_p95_s", "wait_p99_s",
    "hold_p50_s", "hold_p95_s", "hold_p99_s",
)

# keys the smoke's causal (speedup-ledger) section must carry for
# --check-schema (docs/OBSERVABILITY.md §Causal profiler)
CAUSAL_REQUIRED_KEYS = ("schema", "baseline_qps", "cells", "ledger")

# keys every speedup-ledger row must carry
CAUSAL_LEDGER_ROW_KEYS = (
    "phase", "speedup_pct", "predicted_qps", "predicted_gain_qps",
)

# keys every BENCH_HISTORY.jsonl entry must carry (--history appends
# them, --trend validates before trusting the trajectory)
HISTORY_REQUIRED_KEYS = (
    "t", "date", "git_rev", "provenance", "source", "metrics",
)

# the flowprof closed phase set (corda_tpu/observability/flowprof.PHASES,
# mirrored here because the gate is pure JSON arithmetic): a loadtest
# waterfall may only contain these phases, and they must sum to the
# flow-class wall within 5% — conservation is the waterfall's contract
LOADTEST_PHASES = (
    "queue_wait", "device_execute", "host_verify", "wal_fsync_wait",
    "lock_wait", "serialize", "message_transit", "checkpoint",
    "notary_rtt", "engine_other",
)


def _check_waterfall(wf, where: str, problems: list[str]) -> None:
    if not isinstance(wf, dict):
        problems.append(f"{where}: expected an object")
        return
    phases = wf.get("phases")
    wall = wf.get("wall_s")
    if not isinstance(phases, dict) or not isinstance(wall, (int, float)) \
            or isinstance(wall, bool):
        problems.append(f"{where}: missing 'phases' object / numeric "
                        "'wall_s'")
        return
    for name, v in phases.items():
        if name not in LOADTEST_PHASES:
            problems.append(
                f"{where}: unknown phase {name!r} (closed set: "
                + ", ".join(LOADTEST_PHASES) + ")"
            )
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            problems.append(f"{where}: phase {name!r} not a non-negative "
                            "number")
    total = sum(
        v for v in phases.values()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    )
    if wall > 0 and abs(total - wall) > 0.05 * wall:
        problems.append(
            f"{where}: phases sum {total:.6g} deviates from wall_s "
            f"{wall:.6g} by more than 5% (conservation broken)"
        )


def resolve_path(data: dict, path: str):
    """Walk a ``/``-separated path; None when any hop is missing or the
    leaf is not a number."""
    node = data
    for part in path.split("/"):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    if isinstance(node, bool) or not isinstance(node, (int, float)):
        return None
    return float(node)


def load_json(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object")
    return data


def check_schema(result: dict) -> list[str]:
    """Structural validation of a bench result — the no-device CI mode.
    Returns problem strings (empty = ok)."""
    problems: list[str] = []
    present = [p for p in GATED_METRICS if resolve_path(result, p) is not None]
    if not present:
        problems.append(
            "no known gated metric present (expected at least one of: "
            + ", ".join(sorted(GATED_METRICS)) + ")"
        )
    for path in present:
        v = resolve_path(result, path)
        if v is not None and v < 0:
            problems.append(f"{path}: negative value {v}")
    profile = result.get("profile")
    if profile is not None:
        if not isinstance(profile, dict):
            problems.append("profile: expected an object of kernel entries")
        else:
            for kernel, entry in profile.items():
                if not isinstance(entry, dict):
                    problems.append(f"profile/{kernel}: expected an object")
                    continue
                for key in PROFILE_REQUIRED_KEYS:
                    if not isinstance(entry.get(key), (int, float)):
                        problems.append(
                            f"profile/{kernel}: missing numeric {key!r}"
                        )
                eff = entry.get("batch_efficiency")
                if isinstance(eff, (int, float)) and not (0 < eff <= 1.0):
                    problems.append(
                        f"profile/{kernel}: batch_efficiency {eff} "
                        "outside (0, 1]"
                    )
    mfu = result.get("mfu")
    if mfu is not None:
        if not isinstance(mfu, dict):
            problems.append("mfu: expected an object of per-scheme entries")
        else:
            for scheme, entry in mfu.items():
                if scheme == "peak_assumption":
                    continue
                if not isinstance(entry, dict):
                    problems.append(f"mfu/{scheme}: expected an object")
                    continue
                if entry.get("model_only"):
                    # model-only entries (ed25519_batch): pure op-census
                    # numbers with no achieved-rate or utilization — the
                    # deviceless RLC acceptance pin lives here instead.
                    for key in ("ops_per_verify", "savings_vs_per_sig"):
                        v = entry.get(key)
                        if not isinstance(v, (int, float)) \
                                or isinstance(v, bool) or v <= 0:
                            problems.append(
                                f"mfu/{scheme}: missing positive numeric "
                                f"{key!r}"
                            )
                    sav = entry.get("savings_vs_per_sig")
                    if isinstance(sav, (int, float)) \
                            and not isinstance(sav, bool) and sav < 2.0:
                        problems.append(
                            f"mfu/{scheme}: savings_vs_per_sig {sav} below "
                            "the 2x batch-verify acceptance floor"
                        )
                    continue
                for key in ("ops_per_verify_millions",
                            "achieved_int32_gops", "utilization_pct"):
                    v = entry.get(key)
                    if not isinstance(v, (int, float)) \
                            or isinstance(v, bool) or v <= 0:
                        problems.append(
                            f"mfu/{scheme}: missing positive numeric "
                            f"{key!r}"
                        )
                pct = entry.get("utilization_pct")
                if isinstance(pct, (int, float)) and pct > 100:
                    problems.append(
                        f"mfu/{scheme}: utilization_pct {pct} exceeds 100"
                    )
                # internal consistency: achieved == rate × ops/verify
                # (the cross-check that catches a stale model riding a
                # fresh capture)
                rate = resolve_path(result, f"{scheme}_sigs_per_sec")
                opm = entry.get("ops_per_verify_millions")
                ach = entry.get("achieved_int32_gops")
                if (rate and isinstance(opm, (int, float))
                        and isinstance(ach, (int, float)) and ach > 0):
                    want = rate * opm * 1e6 / 1e9
                    if abs(want - ach) > 0.05 * max(want, ach):
                        problems.append(
                            f"mfu/{scheme}: achieved_int32_gops {ach} "
                            f"inconsistent with {scheme}_sigs_per_sec × "
                            f"ops_per_verify ({want:.1f})"
                        )
    devices = result.get("devices")
    if devices is not None:
        if not isinstance(devices, dict):
            problems.append(
                "devices: expected an object of per-ordinal entries"
            )
        else:
            for ordinal, entry in devices.items():
                if not str(ordinal).isdigit():
                    problems.append(
                        f"devices/{ordinal}: ordinal key is not an integer"
                    )
                if not isinstance(entry, dict):
                    problems.append(
                        f"devices/{ordinal}: expected an object"
                    )
                    continue
                for key in DEVICES_REQUIRED_KEYS:
                    v = entry.get(key)
                    if not isinstance(v, (int, float)) \
                            or isinstance(v, bool):
                        problems.append(
                            f"devices/{ordinal}: missing numeric {key!r}"
                        )
                    elif v < 0:
                        problems.append(
                            f"devices/{ordinal}: negative {key} {v}"
                        )
                rows = entry.get("rows")
                padded = entry.get("padded_rows")
                if (isinstance(rows, (int, float))
                        and isinstance(padded, (int, float))
                        and rows > padded):
                    problems.append(
                        f"devices/{ordinal}: rows {rows} exceed padded "
                        f"lanes {padded}"
                    )
    resilience = result.get("resilience")
    if resilience is not None:
        if not isinstance(resilience, dict):
            problems.append("resilience: expected an object")
        else:
            for key in RESILIENCE_REQUIRED_KEYS:
                v = resilience.get(key)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    problems.append(
                        f"resilience: missing numeric {key!r}"
                    )
                elif v < 0:
                    problems.append(f"resilience: negative {key} {v}")
            fired = resilience.get("hedge_fired")
            won = (resilience.get("hedge_won_host"),
                   resilience.get("hedge_won_device"))
            if (isinstance(fired, (int, float))
                    and all(isinstance(w, (int, float)) for w in won)
                    and sum(won) > fired):
                problems.append(
                    f"resilience: hedge winners {sum(won)} exceed fired "
                    f"hedges {fired} (a hedge resolves at most one winner)"
                )
            state = resilience.get("breaker_state")
            if isinstance(state, (int, float)) and state not in (0, 1, 2):
                problems.append(
                    f"resilience: breaker_state {state} outside 0/1/2"
                )
    durability = result.get("durability")
    if durability is not None:
        if not isinstance(durability, dict):
            problems.append("durability: expected an object")
        else:
            for key in DURABILITY_REQUIRED_KEYS:
                v = durability.get(key)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    problems.append(f"durability: missing numeric {key!r}")
                elif v < 0:
                    problems.append(f"durability: negative {key} {v}")
            p50 = durability.get("wal_fsync_p50_ms")
            p99 = durability.get("wal_fsync_p99_ms")
            if (isinstance(p50, (int, float)) and isinstance(p99, (int, float))
                    and not isinstance(p50, bool) and not isinstance(p99, bool)
                    and p99 < p50):
                problems.append(
                    f"durability: wal_fsync_p99_ms {p99} below p50 {p50} "
                    "(quantiles must be monotone)"
                )
    batchverify = result.get("batchverify")
    if batchverify is not None:
        if not isinstance(batchverify, dict):
            problems.append("batchverify: expected an object")
        else:
            for key in BATCHVERIFY_REQUIRED_KEYS:
                v = batchverify.get(key)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    problems.append(f"batchverify: missing numeric {key!r}")
                elif v < 0:
                    problems.append(f"batchverify: negative {key} {v}")
            for flag in ("rlc_parity_ok", "bls_aggregate_ok"):
                v = batchverify.get(flag)
                if isinstance(v, (int, float)) and not isinstance(v, bool) \
                        and v != 1:
                    problems.append(
                        f"batchverify: {flag} is {v} (the pass must prove "
                        "parity, not merely run)"
                    )
            exp = batchverify.get("offenders_expected")
            got = batchverify.get("offenders_found")
            if (isinstance(exp, (int, float)) and isinstance(got, (int, float))
                    and not isinstance(exp, bool) and not isinstance(got, bool)
                    and exp != got):
                problems.append(
                    f"batchverify: bisection found {got} offenders, "
                    f"planted {exp}"
                )
    multichip = result.get("multichip")
    if multichip is not None:
        if not isinstance(multichip, dict):
            problems.append("multichip: expected an object")
        else:
            def num(key):
                v = multichip.get(key)
                return v if isinstance(v, (int, float)) \
                    and not isinstance(v, bool) else None

            for key in MULTICHIP_REQUIRED_KEYS:
                if num(key) is None:
                    problems.append(f"multichip: missing numeric {key!r}")
                elif num(key) < 0:
                    problems.append(
                        f"multichip: negative {key} {num(key)}"
                    )
            se = num("scaling_efficiency")
            if se is not None and not (0.8 <= se <= 1.0):
                problems.append(
                    f"multichip: scaling_efficiency {se} outside "
                    "[0.8, 1.0] (the stripe must stay balanced)"
                )
            n, hit = num("n_devices"), num("ordinals_hit")
            if n is not None and hit is not None and hit > n:
                problems.append(
                    f"multichip: ordinals_hit {hit} exceed n_devices {n}"
                )
            rows, mx = num("rows"), num("max_ordinal_rows")
            if (se is not None and n is not None and rows is not None
                    and mx is not None and n * mx > 0
                    and abs(se - rows / (n * mx)) > 0.01):
                problems.append(
                    f"multichip: scaling_efficiency {se} inconsistent "
                    f"with rows/(n_devices × max_ordinal_rows) "
                    f"({rows / (n * mx):.3f})"
                )
            for flag in ("allgather_parity_ok", "mega_parity_ok"):
                v = num(flag)
                if v is not None and v != 1:
                    problems.append(
                        f"multichip: {flag} is {v} (the pass must prove "
                        "parity, not merely run)"
                    )
    loadtest = result.get("loadtest")
    if loadtest is None and result.get("mode") == "open-loop-poisson":
        # a standalone LOADTEST.json (tools_loadgen.py) IS the section
        loadtest = result
    if loadtest is not None:
        if not isinstance(loadtest, dict):
            problems.append("loadtest: expected an object")
        else:
            steps = loadtest.get("steps")
            if not isinstance(steps, list) or not steps:
                problems.append("loadtest: missing non-empty 'steps' list")
                steps = []
            for i, step in enumerate(steps):
                where = f"loadtest/steps[{i}]"
                if not isinstance(step, dict):
                    problems.append(f"{where}: expected an object")
                    continue
                for key in LOADTEST_STEP_REQUIRED_KEYS:
                    v = step.get(key)
                    if not isinstance(v, (int, float)) \
                            or isinstance(v, bool):
                        problems.append(f"{where}: missing numeric {key!r}")
                    elif v < 0:
                        problems.append(f"{where}: negative {key} {v}")
                p50, p99 = step.get("p50_s"), step.get("p99_s")
                if (isinstance(p50, (int, float))
                        and isinstance(p99, (int, float))
                        and not isinstance(p50, bool)
                        and not isinstance(p99, bool) and p99 < p50):
                    problems.append(
                        f"{where}: p99_s {p99} below p50_s {p50} "
                        "(quantiles must be monotone)"
                    )
                comp, off = step.get("completed"), step.get("offered")
                if (isinstance(comp, (int, float))
                        and isinstance(off, (int, float))
                        and not isinstance(comp, bool)
                        and not isinstance(off, bool) and comp > off):
                    problems.append(
                        f"{where}: completed {comp} exceeds offered {off} "
                        "(an open-loop step cannot complete more than it "
                        "offered)"
                    )
                if "waterfall" in step:
                    _check_waterfall(step["waterfall"],
                                     f"{where}/waterfall", problems)
            knee = loadtest.get("knee")
            kq = loadtest.get("knee_qps")
            if kq is not None and (not isinstance(kq, (int, float))
                                   or isinstance(kq, bool) or kq <= 0):
                problems.append(
                    f"loadtest: knee_qps {kq!r} is not a positive number"
                )
            if knee is not None:
                if not isinstance(knee, dict):
                    problems.append("loadtest/knee: expected an object")
                else:
                    if "waterfall" in knee:
                        _check_waterfall(knee["waterfall"],
                                         "loadtest/knee/waterfall",
                                         problems)
                    kp50, kp99 = knee.get("p50_s"), knee.get("p99_s")
                    if (isinstance(kp50, (int, float))
                            and isinstance(kp99, (int, float))
                            and not isinstance(kp50, bool)
                            and not isinstance(kp99, bool)
                            and kp99 < kp50):
                        problems.append(
                            f"loadtest/knee: p99_s {kp99} below p50_s "
                            f"{kp50} (quantiles must be monotone)"
                        )
    overload = result.get("overload")
    if overload is not None:
        if not isinstance(overload, dict):
            problems.append("overload: expected an object")
        elif not overload.get("enabled", True):
            # a disabled capture ({"enabled": false}) carries no numbers
            pass
        else:
            def onum(key):
                v = overload.get(key)
                return v if isinstance(v, (int, float)) \
                    and not isinstance(v, bool) else None

            for key in OVERLOAD_REQUIRED_KEYS:
                if onum(key) is None:
                    problems.append(f"overload: missing numeric {key!r}")
                elif onum(key) < 0:
                    problems.append(
                        f"overload: negative {key} {onum(key)}"
                    )
            for flag in ("goodput_floor_ok", "recovery_ok",
                         "brownout_order_ok", "retry_budget_ok"):
                v = onum(flag)
                if v is not None and v != 1:
                    problems.append(
                        f"overload: {flag} is {v:g} (the pass must prove "
                        "graceful degradation, not merely run)"
                    )
            base, storm = onum("baseline_goodput_qps"), \
                onum("storm_goodput_qps")
            ratio = onum("goodput_ratio")
            if (base is not None and storm is not None
                    and ratio is not None and base > 0
                    and abs(ratio - storm / base) > 0.01):
                problems.append(
                    f"overload: goodput_ratio {ratio} inconsistent with "
                    f"storm/baseline ({storm / base:.3f})"
                )
            granted, earned = onum("retry_budget_granted"), \
                onum("retry_budget_earned")
            if (granted is not None and earned is not None
                    and granted > earned):
                problems.append(
                    f"overload: retry_budget_granted {granted:g} exceeds "
                    f"budget earned {earned:g} (the token bucket cannot "
                    "grant more than fresh sends funded)"
                )
            retx = onum("retransmits")
            if (retx is not None and granted is not None
                    and retx > 2 * granted + 16):
                problems.append(
                    f"overload: retransmits {retx:g} exceed "
                    f"2×retry_budget_granted+16 ({2 * granted + 16:g}) — "
                    "retry volume escaped the budget"
                )
            wall, limit = onum("recovery_wall_s"), \
                onum("recovery_wall_limit_s")
            if wall is not None and limit is not None and wall > limit:
                problems.append(
                    f"overload: recovery_wall_s {wall:g} exceeds the "
                    f"{limit:g}s bound (recovery must be prompt, not "
                    "eventual)"
                )
    cluster = result.get("cluster")
    if cluster is not None:
        if not isinstance(cluster, dict):
            problems.append("cluster: expected an object")
        else:
            def cnum(key):
                v = cluster.get(key)
                return v if isinstance(v, (int, float)) \
                    and not isinstance(v, bool) else None

            for key in CLUSTER_REQUIRED_KEYS:
                if cnum(key) is None:
                    problems.append(f"cluster: missing numeric {key!r}")
                elif cnum(key) < 0:
                    problems.append(f"cluster: negative {key} {cnum(key)}")
            hops = cnum("hops")
            if hops is not None and hops < 2:
                problems.append(
                    f"cluster: assembled trace has {hops:g} hops — a "
                    "notarised payment must cross the wire at least twice"
                )
            tp50, tp99 = cnum("transit_p50_s"), cnum("transit_p99_s")
            if tp50 is not None and tp99 is not None and tp99 < tp50:
                problems.append(
                    f"cluster: transit_p99_s {tp99} below transit_p50_s "
                    f"{tp50} (quantiles must be monotone)"
                )
            lo, mid, hi = (cnum("node_p99_min_s"), cnum("rollup_p99_s"),
                           cnum("node_p99_max_s"))
            if (lo is not None and mid is not None and hi is not None
                    and not (lo <= mid <= hi)):
                problems.append(
                    f"cluster: rollup_p99_s {mid} outside the per-node "
                    f"envelope [{lo}, {hi}] (rollup must reconcile with "
                    "its members)"
                )
            rec = cnum("pernode_reconcile_ok")
            if rec is not None and rec != 1:
                problems.append(
                    f"cluster: pernode_reconcile_ok is {rec:g} (federated "
                    "sections must equal each node's local snapshot)"
                )
    statestore = result.get("statestore")
    if statestore is not None:
        if not isinstance(statestore, dict):
            problems.append("statestore: expected an object")
        elif not statestore.get("enabled", True):
            # a disabled capture ({"enabled": false}) carries no numbers
            pass
        else:
            def snum(key):
                v = statestore.get(key)
                return v if isinstance(v, (int, float)) \
                    and not isinstance(v, bool) else None

            for key in STATESTORE_REQUIRED_KEYS:
                if snum(key) is None:
                    problems.append(f"statestore: missing numeric {key!r}")
                elif snum(key) < 0:
                    problems.append(
                        f"statestore: negative {key} {snum(key)}"
                    )
            for key in ("occupancy_low", "occupancy_high"):
                v = snum(key)
                if v is not None and v > 1.0:
                    problems.append(
                        f"statestore: {key} {v} exceeds 1.0 (occupancy is "
                        "live rows over table slots)"
                    )
            lo, hi = snum("occupancy_low"), snum("occupancy_high")
            if lo is not None and hi is not None and hi <= lo:
                problems.append(
                    f"statestore: occupancy_high {hi} not above "
                    f"occupancy_low {lo} (the pass must measure the table "
                    "at two distinct load points)"
                )
            for flag in ("verdict_parity", "digest_parity"):
                v = snum(flag)
                if v is not None and v != 1:
                    problems.append(
                        f"statestore: {flag} is {v:g} (the pass must prove "
                        "bit-parity with the host oracle, not merely run)"
                    )
    tl = result.get("timeline")
    if tl is not None:
        if not isinstance(tl, dict):
            problems.append("timeline: expected an object")
        elif not tl.get("enabled", True):
            # a disabled capture ({"enabled": false}) carries no numbers
            pass
        else:
            def tnum(key):
                v = tl.get(key)
                return v if isinstance(v, (int, float)) \
                    and not isinstance(v, bool) else None

            # two shapes land here: the smoke's scored section (flat
            # counts + a ``rings`` name→points map) and a RAW
            # ``TimelineRecorder.snapshot()`` (``series`` is a dict of
            # {kind, points} — what ``tools_loadgen.py --timeline``
            # embeds). The raw shape skips the smoke-only scoring keys
            # but gets the same timestamp/ring/quantile checks.
            raw_snapshot = isinstance(tl.get("series"), dict)
            if raw_snapshot:
                rings = {
                    name: (s or {}).get("points")
                    for name, s in tl["series"].items()
                    if isinstance(s, dict)
                }
                if not rings:
                    problems.append(
                        "timeline: snapshot carries no series"
                    )
            else:
                rings = tl.get("rings")
                for key in TIMELINE_REQUIRED_KEYS:
                    if tnum(key) is None:
                        problems.append(
                            f"timeline: missing numeric {key!r}"
                        )
                    elif tnum(key) < 0:
                        problems.append(
                            f"timeline: negative {key} {tnum(key)}"
                        )
                for key in ("ticks", "series", "counter_series",
                            "timer_series"):
                    v = tnum(key)
                    if v is not None and v < 1:
                        problems.append(
                            f"timeline: {key} is {v:g} — the pass must "
                            "record at least one"
                        )
            if tnum("cadence_s") is not None and tnum("cadence_s") <= 0:
                problems.append(
                    f"timeline: cadence_s {tnum('cadence_s')} is not "
                    "positive"
                )
            ts = tl.get("timestamps")
            if not isinstance(ts, list) or not ts or not all(
                isinstance(t, (int, float)) and not isinstance(t, bool)
                for t in ts
            ):
                problems.append(
                    "timeline: missing non-empty numeric 'timestamps' list"
                )
            elif any(b < a for a, b in zip(ts, ts[1:])):
                problems.append(
                    "timeline: timestamps are not monotone nondecreasing"
                )
            if not isinstance(rings, dict) or not rings:
                if not raw_snapshot:
                    problems.append(
                        "timeline: missing non-empty 'rings' object"
                    )
            else:
                for name, ring in rings.items():
                    if not isinstance(ring, list) or not ring or not all(
                        isinstance(v, (int, float))
                        and not isinstance(v, bool) for v in ring
                    ):
                        problems.append(
                            f"timeline/rings/{name}: expected a non-empty "
                            "numeric list"
                        )
                # interval quantiles must be monotone: for every
                # <timer>.p50_s ring with a <timer>.p99_s sibling, the
                # p99 point can never sit below the p50 point of the
                # same interval (align on the trailing points — a series
                # may have started later than its sibling)
                for name, p50 in rings.items():
                    if not name.endswith(".p50_s"):
                        continue
                    sibling = name[: -len(".p50_s")] + ".p99_s"
                    p99 = rings.get(sibling)
                    if not (isinstance(p50, list) and isinstance(p99, list)):
                        continue
                    n = min(len(p50), len(p99))
                    for i in range(1, n + 1):
                        a, b = p50[-i], p99[-i]
                        if (isinstance(a, (int, float))
                                and isinstance(b, (int, float))
                                and not isinstance(a, bool)
                                and not isinstance(b, bool) and b < a):
                            problems.append(
                                f"timeline/rings/{sibling}: point {b} "
                                f"below {name} point {a} (interval "
                                "quantiles must be monotone)"
                            )
                            break
            v = tnum("flight_roundtrip_ok")
            if v is not None and v != 1:
                problems.append(
                    f"timeline: flight_roundtrip_ok is {v:g} (the pass "
                    "must prove the dump round-trips, not merely run)"
                )
            v = tnum("burn_alerts")
            if v is not None and v < 1:
                problems.append(
                    f"timeline: burn_alerts is {v:g} — the synthetic "
                    "burn-rate breach must fire"
                )
    contention = result.get("contention")
    if contention is not None:
        if not isinstance(contention, dict):
            problems.append("contention: expected an object")
        elif not contention.get("enabled", True):
            # a disabled capture ({"enabled": false}) carries no numbers
            pass
        else:
            sites = contention.get("sites")
            if not isinstance(sites, dict) or not sites:
                problems.append(
                    "contention: missing non-empty 'sites' object"
                )
                sites = {}
            for name, site in sites.items():
                if not isinstance(site, dict):
                    problems.append(
                        f"contention/sites/{name}: expected an object"
                    )
                    continue

                def cnum(key, _site=site):
                    v = _site.get(key)
                    return v if isinstance(v, (int, float)) \
                        and not isinstance(v, bool) else None

                for key in CONTENTION_SITE_REQUIRED_KEYS:
                    if cnum(key) is None:
                        problems.append(
                            f"contention/sites/{name}: missing numeric "
                            f"{key!r}"
                        )
                    elif cnum(key) < 0:
                        problems.append(
                            f"contention/sites/{name}: negative {key} "
                            f"{cnum(key)}"
                        )
                acq, cont = cnum("acquires"), cnum("contended")
                if acq is not None and cont is not None and cont > acq:
                    problems.append(
                        f"contention/sites/{name}: contended {cont:g} "
                        f"exceeds acquires {acq:g} (every contended "
                        "acquire is still an acquire)"
                    )
                # reservoir quantiles must be monotone, per triple
                for stem in ("wait", "hold"):
                    q50 = cnum(f"{stem}_p50_s")
                    q95 = cnum(f"{stem}_p95_s")
                    q99 = cnum(f"{stem}_p99_s")
                    if None not in (q50, q95, q99) \
                            and not (q50 <= q95 <= q99):
                        problems.append(
                            f"contention/sites/{name}: {stem} quantiles "
                            f"not monotone (p50 {q50:g}, p95 {q95:g}, "
                            f"p99 {q99:g})"
                        )
            top = contention.get("top")
            if not isinstance(top, list) or not top:
                problems.append(
                    "contention: missing non-empty 'top' list"
                )
            else:
                waits = [
                    r.get("wait_total_s") for r in top
                    if isinstance(r, dict)
                ]
                if len(waits) != len(top) or not all(
                    isinstance(w, (int, float)) and not isinstance(w, bool)
                    for w in waits
                ):
                    problems.append(
                        "contention/top: every row must be an object "
                        "with numeric 'wait_total_s'"
                    )
                elif any(b > a for a, b in zip(waits, waits[1:])):
                    problems.append(
                        "contention/top: rows not sorted by descending "
                        "wait_total_s"
                    )
            edges = contention.get("edges")
            if not isinstance(edges, list):
                problems.append("contention: missing 'edges' list")
            else:
                for i, e in enumerate(edges):
                    if not isinstance(e, dict) \
                            or not isinstance(e.get("holder"), str) \
                            or not isinstance(e.get("waiter"), str):
                        problems.append(
                            f"contention/edges[{i}]: expected an object "
                            "with string 'holder'/'waiter'"
                        )
                        continue
                    w = e.get("wait_s")
                    if not isinstance(w, (int, float)) \
                            or isinstance(w, bool) or w < 0:
                        problems.append(
                            f"contention/edges[{i}]: 'wait_s' not a "
                            "non-negative number"
                        )
    causal = result.get("causal")
    if causal is not None:
        if not isinstance(causal, dict):
            problems.append("causal: expected an object")
        elif not causal.get("enabled", True):
            # run-on-demand: no recorded ledger yet
            pass
        else:
            for key in CAUSAL_REQUIRED_KEYS:
                if key not in causal:
                    problems.append(f"causal: missing {key!r}")
            base = causal.get("baseline_qps")
            if base is not None and (
                not isinstance(base, (int, float))
                or isinstance(base, bool) or base <= 0
            ):
                problems.append(
                    f"causal: baseline_qps {base!r} is not a positive "
                    "number"
                )
            cells = causal.get("cells")
            if isinstance(cells, list):
                for i, c in enumerate(cells):
                    if not isinstance(c, dict):
                        problems.append(
                            f"causal/cells[{i}]: expected an object"
                        )
                        continue
                    q = c.get("experiment_qps")
                    if not isinstance(q, (int, float)) \
                            or isinstance(q, bool) or q <= 0:
                        problems.append(
                            f"causal/cells[{i}]: 'experiment_qps' not a "
                            "positive number (the probe must have run)"
                        )
            elif cells is not None:
                problems.append("causal: 'cells' is not a list")
            ledger = causal.get("ledger")
            if isinstance(ledger, list):
                gains = []
                for i, row in enumerate(ledger):
                    if not isinstance(row, dict):
                        problems.append(
                            f"causal/ledger[{i}]: expected an object"
                        )
                        continue
                    for key in CAUSAL_LEDGER_ROW_KEYS:
                        if key not in row:
                            problems.append(
                                f"causal/ledger[{i}]: missing {key!r}"
                            )
                    g = row.get("predicted_gain_qps")
                    if isinstance(g, (int, float)) \
                            and not isinstance(g, bool):
                        gains.append(g)
                if len(gains) == len(ledger) and any(
                    b > a for a, b in zip(gains, gains[1:])
                ):
                    problems.append(
                        "causal/ledger: rows not sorted by descending "
                        "predicted_gain_qps (the ledger must rank "
                        "payoffs)"
                    )
            elif ledger is not None:
                problems.append("causal: 'ledger' is not a list")
            # a synthetic run must carry the planted-bottleneck
            # validation and it must have passed (±tol) — the ledger is
            # only trustworthy if its math was checked against a
            # measured gain this run
            val = causal.get("validation")
            if causal.get("source") == "synthetic" \
                    and not isinstance(val, dict):
                problems.append(
                    "causal: synthetic run missing 'validation' object"
                )
            if isinstance(val, dict):
                if not val.get("ok"):
                    problems.append(
                        "causal/validation: ok is not true (the "
                        "planted-bottleneck prediction must land within "
                        "tolerance of the measured gain)"
                    )
                rel, tol = val.get("rel_err"), val.get("tol")
                if isinstance(rel, (int, float)) \
                        and isinstance(tol, (int, float)) \
                        and not isinstance(rel, bool) \
                        and not isinstance(tol, bool) and rel > tol:
                    problems.append(
                        f"causal/validation: rel_err {rel:g} exceeds "
                        f"tol {tol:g}"
                    )
    return problems


def write_baseline(result: dict, result_path: str, baseline_path: str) -> int:
    metrics = {}
    for path, (direction, tol) in sorted(GATED_METRICS.items()):
        v = resolve_path(result, path)
        if v is None:
            continue
        metrics[path] = {
            "baseline": v, "rel_tol": tol, "direction": direction,
        }
    if not metrics:
        print("perf-gate: refusing to write an empty baseline "
              "(no gated metric found in the result)")
        return 1
    doc = {
        "schema": 1,
        "source": os.path.basename(result_path),
        "captured_at": result.get("captured_at"),
        "device": result.get("device"),
        "metrics": metrics,
    }
    tmp = baseline_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, baseline_path)
    print(f"perf-gate: wrote {baseline_path} ({len(metrics)} metrics)")
    return 0


# ---------------------------------------------------------- perf history

def _git_rev() -> str:
    """Short rev of the repo this tool lives in; "unknown" when git is
    unavailable (a vendored copy, a tarball CI runner)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except Exception:
        return "unknown"


def history_entry(result: dict, source: str) -> dict | None:
    """One BENCH_HISTORY.jsonl record for a bench result: timestamp,
    git rev, chip-vs-deviceless provenance, and every gated metric the
    result carries. None when the result carries no gated metric — an
    empty entry would pollute the trajectory with unplottable points."""
    import time as _time

    metrics = {}
    for path in sorted(GATED_METRICS):
        v = resolve_path(result, path)
        if v is not None:
            metrics[path] = v
    if not metrics:
        return None
    now = _time.time()
    return {
        "t": now,
        "date": _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(now)),
        "git_rev": _git_rev(),
        "provenance": result.get("device") or "deviceless",
        "source": source,
        "metrics": metrics,
    }


def validate_history_entry(entry, where: str) -> list[str]:
    problems: list[str] = []
    if not isinstance(entry, dict):
        return [f"{where}: expected an object"]
    for key in HISTORY_REQUIRED_KEYS:
        if key not in entry:
            problems.append(f"{where}: missing {key!r}")
    t = entry.get("t")
    if "t" in entry and (not isinstance(t, (int, float))
                         or isinstance(t, bool) or t <= 0):
        problems.append(f"{where}: 't' is not a positive number")
    for key in ("date", "git_rev", "provenance", "source"):
        v = entry.get(key)
        if key in entry and (not isinstance(v, str) or not v):
            problems.append(f"{where}: {key!r} is not a non-empty string")
    metrics = entry.get("metrics")
    if "metrics" in entry:
        if not isinstance(metrics, dict) or not metrics:
            problems.append(f"{where}: 'metrics' is not a non-empty object")
        else:
            for path, v in metrics.items():
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    problems.append(
                        f"{where}: metric {path!r} is not numeric"
                    )
    return problems


def load_history(history_path: str) -> tuple[list[dict], list[str]]:
    """Parse + validate BENCH_HISTORY.jsonl → (entries, problems)."""
    entries: list[dict] = []
    problems: list[str] = []
    try:
        with open(history_path) as f:
            raw_lines = f.readlines()
    except OSError as e:
        return [], [f"cannot read {history_path}: {e}"]
    for i, raw in enumerate(raw_lines, 1):
        raw = raw.strip()
        if not raw:
            continue
        where = f"{os.path.basename(history_path)}:{i}"
        try:
            entry = json.loads(raw)
        except json.JSONDecodeError as e:
            problems.append(f"{where}: not JSON ({e})")
            continue
        probs = validate_history_entry(entry, where)
        problems.extend(probs)
        if not probs:
            entries.append(entry)
    return entries, problems


def append_history(result: dict, source: str,
                   history_path: str = HISTORY_DEFAULT) -> int:
    """Append one validated history record; rc 0/1 (CLI contract)."""
    entry = history_entry(result, source)
    if entry is None:
        print("perf-gate: refusing to append an empty history entry "
              "(no gated metric found in the result)")
        return 1
    probs = validate_history_entry(entry, "new entry")
    if probs:  # self-check: a bug here must not corrupt the trajectory
        print("perf-gate: refusing to append a malformed history entry:")
        for p in probs:
            print(f"  {p}")
        return 1
    with open(history_path, "a") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")
    print(f"perf-gate: appended {entry['git_rev']}/"
          f"{entry['provenance']} to {history_path} "
          f"({len(entry['metrics'])} metrics)")
    return 0


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: list[float]) -> str:
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK[0] * len(values)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / (hi - lo) * (len(_SPARK) - 1)))]
        for v in values
    )


def run_trend(history_path: str, window: int) -> int:
    """Render per-metric trajectories over the history file and FAIL on
    any metric strictly monotonically worsening across its last
    ``window`` entries (direction-aware: a rate falling every capture, a
    latency rising every capture). One noisy point breaks the streak —
    by design; the sentinel pages on a trend, not a blip."""
    entries, problems = load_history(history_path)
    if problems:
        print(f"perf-gate: history problems in {history_path}:")
        for p in problems:
            print(f"  {p}")
        return 1
    if not entries:
        print(f"perf-gate: no history entries in {history_path}")
        return 1
    window = max(2, int(window))
    regressions: list[str] = []
    metric_paths = sorted({
        p for e in entries for p in e.get("metrics", {})
    })
    for path in metric_paths:
        series = [
            (e["git_rev"], float(e["metrics"][path]))
            for e in entries if path in e.get("metrics", {})
        ]
        values = [v for _, v in series]
        direction = GATED_METRICS.get(path, ("higher", 0.0))[0]
        tail = values[-window:]
        trajectory = " -> ".join(f"{v:g}" for v in tail)
        regressed = False
        if len(tail) >= window:
            if direction == "higher":
                regressed = all(b < a for a, b in zip(tail, tail[1:]))
            else:
                regressed = all(b > a for a, b in zip(tail, tail[1:]))
        status = "REGRESSING" if regressed else "ok"
        print(f"perf-gate: trend {status} {path} "
              f"[{_sparkline(values)}] {trajectory} "
              f"({direction} is better, {len(values)} captures)")
        if regressed:
            regressions.append(
                f"{path}: {trajectory} — worsened {window - 1}x in a row "
                f"({series[-1][0]} is the latest rev)"
            )
    if regressions:
        print(f"perf-gate: {len(regressions)} monotone regression(s) over "
              f"the last {window} entries:")
        for r in regressions:
            print(f"  {r}")
        return 1
    print(f"perf-gate: trend ok ({len(metric_paths)} metrics, "
          f"{len(entries)} history entries)")
    return 0


def run_gate(result: dict, baseline: dict) -> int:
    metrics = baseline.get("metrics")
    if not isinstance(metrics, dict) or not metrics:
        print("perf-gate: baseline has no metrics table")
        return 1
    failures, missing, passed = [], [], 0
    for path, spec in sorted(metrics.items()):
        base = spec.get("baseline")
        tol = float(spec.get("rel_tol", 0.2))
        direction = spec.get("direction", "higher")
        value = resolve_path(result, path)
        if value is None:
            missing.append(path)
            continue
        if not isinstance(base, (int, float)):
            failures.append(f"{path}: baseline entry is not numeric")
            continue
        if direction == "higher":
            bound = base * (1.0 - tol)
            ok = value >= bound
            verdict = f"value {value:g} >= floor {bound:g}"
        else:
            bound = base * (1.0 + tol)
            ok = value <= bound
            verdict = f"value {value:g} <= ceiling {bound:g}"
        status = "PASS" if ok else "FAIL"
        print(f"perf-gate: {status} {path}: {verdict} "
              f"(baseline {base:g}, tol {tol:.0%}, {direction} is better)")
        if ok:
            passed += 1
        else:
            failures.append(
                f"{path}: {value:g} vs baseline {base:g} "
                f"(allowed {'-' if direction == 'higher' else '+'}{tol:.0%})"
            )
    for path in missing:
        print(f"perf-gate: SKIP {path}: not present in result")
    if passed == 0 and not failures:
        print("perf-gate: result contains none of the baseline's metrics")
        return 1
    if failures:
        print(f"perf-gate: {len(failures)} regression(s):")
        for f in failures:
            print(f"  {f}")
        return 1
    print(f"perf-gate: ok ({passed} metrics within tolerance, "
          f"{len(missing)} skipped)")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--result",
                    help="bench JSON to gate (bench.py / --smoke output "
                         "or BENCH_LOCAL.json)")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help="baseline path (default: PERF_BASELINE.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record the result as the new baseline and exit")
    ap.add_argument("--check-schema", action="store_true",
                    help="validate the result's structure only (no "
                         "baseline, no device)")
    ap.add_argument("--history", action="store_true",
                    help="append the result's gated metrics to the "
                         "history file and exit")
    ap.add_argument("--history-file", default=HISTORY_DEFAULT,
                    help="history path (default: BENCH_HISTORY.jsonl)")
    ap.add_argument("--trend", action="store_true",
                    help="render per-metric trajectories from the history "
                         "file; fail on monotone regression (no --result "
                         "needed)")
    ap.add_argument("--trend-window", type=int, default=3,
                    help="entries a metric must worsen across, "
                         "consecutively, to fail --trend (default 3)")
    args = ap.parse_args(argv)

    if args.trend:
        return run_trend(args.history_file, args.trend_window)

    if not args.result:
        ap.error("--result is required (except with --trend)")

    try:
        result = load_json(args.result)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perf-gate: cannot read result {args.result}: {e}")
        return 2

    if args.history:
        return append_history(result, os.path.basename(args.result),
                              args.history_file)

    if args.check_schema:
        problems = check_schema(result)
        if problems:
            print(f"perf-gate: schema problems in {args.result}:")
            for p in problems:
                print(f"  {p}")
            return 1
        print(f"perf-gate: schema ok ({args.result})")
        return 0

    if args.write_baseline:
        return write_baseline(result, args.result, args.baseline)

    try:
        baseline = load_json(args.baseline)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perf-gate: cannot read baseline {args.baseline}: {e} "
              "(generate one with --write-baseline)")
        return 2
    return run_gate(result, baseline)


if __name__ == "__main__":
    sys.exit(main())
