"""Open-loop load generator CLI (docs/LOAD_HARNESS.md).

Drives ``corda_tpu/tools/loadharness.py`` — Poisson arrivals over an
in-process mocknet at a stepped qps ramp, each step scored through the
SLO monitor — and writes ``LOADTEST.json`` (knee qps, per-step
p50/p99/shed rate, the flowprof waterfall at the knee). The schema is
validated by ``tools_perf_gate.py --result LOADTEST.json
--check-schema``.

    python tools_loadgen.py                            # default ramp
    python tools_loadgen.py --qps 5,10,20 --duration 5
    python tools_loadgen.py --chaos --durable          # under fault load
    python tools_loadgen.py --workload issue --out /tmp/LOADTEST.json

Knobs:

    --qps A,B,C      arrival-rate steps (flows/sec; default 4,8,16)
    --duration S     seconds of arrivals per step (default 5)
    --p99 S          per-step p99 SLO bound (default 2.0)
    --max-error-rate F  error+shed rate bound (default 0.05)
    --max-inflight N open-loop shed bound (default 256)
    --workload W     payment | issue (default payment)
    --seed N         arrival-process seed (default 2026)
    --chaos          inject message drop/delay while the ramp runs
    --durable        WAL-backed checkpoints on every node
    --resilience     self-healing serving policy
    --device         device-batched signature verification
    --sampler        attach the stack sampler's folded stacks
    --out PATH       output path (default LOADTEST.json)

Overload certification (docs/OVERLOAD.md) rides the same CLI: after the
ramp locates the knee, ``--overload`` re-runs the harness topology as a
three-phase metastability scenario — baseline at the knee, a storm at
``--overload-factor``× the knee under partition bursts + message chaos,
then recovery back at the knee — with deadline propagation, retry
budgets and adaptive admission enabled. The scored ``overload`` section
(goodput floor, brownout order, retry-budget reconciliation, bounded
recovery wall) merges into LOADTEST.json and is validated by the same
``--check-schema``; any failed certification flag exits nonzero.

    --overload            run the metastability scenario past the knee
    --overload-factor F   storm arrival multiple of the knee (default 3)
    --storm S             storm duration in seconds (default 6)
    --recovery S          recovery wall bound in seconds (default 30)

Causal profiling (docs/OBSERVABILITY.md §Causal profiler) also rides
the ramp: after the knee is located, ``--causal`` runs COZ-style
virtual-speedup experiments — one fresh single-step probe at the knee's
arrival rate per (phase, speedup%) cell, with calibrated delays
inserted into every *other* flowprof phase — and merges the resulting
``causal`` section (the speedup ledger ranking phases by predicted
knee-qps payoff) into LOADTEST.json, validated by the same
``--check-schema``.

    --causal              run virtual-speedup experiments at the knee
    --causal-phases P,..  flowprof phases to experiment on
                          (default host_verify,serialize,checkpoint)
    --causal-speedups N,..  virtual speedup percentages (default 50)
    --causal-duration S   seconds of arrivals per probe (default 4;
                          longer probes = less noisy ledger)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

ROOT = Path(__file__).parent
sys.path.insert(0, str(ROOT))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--qps", default="4,8,16",
                    help="comma-separated qps steps (default 4,8,16)")
    ap.add_argument("--duration", type=float, default=5.0,
                    help="seconds of arrivals per step (default 5)")
    ap.add_argument("--p99", type=float, default=2.0,
                    help="per-step p99 SLO bound in seconds (default 2)")
    ap.add_argument("--max-error-rate", type=float, default=0.05,
                    help="error+shed rate bound (default 0.05)")
    ap.add_argument("--max-inflight", type=int, default=256,
                    help="open-loop shed bound (default 256)")
    ap.add_argument("--workload", choices=("payment", "issue"),
                    default="payment")
    ap.add_argument("--seed", type=int, default=2026)
    ap.add_argument("--chaos", action="store_true",
                    help="run the ramp under injected message drop/delay")
    ap.add_argument("--durable", action="store_true",
                    help="WAL-backed checkpoints on every node")
    ap.add_argument("--resilience", action="store_true",
                    help="self-healing serving policy")
    ap.add_argument("--device", action="store_true",
                    help="device-batched signature verification")
    ap.add_argument("--sampler", action="store_true",
                    help="attach the stack sampler's folded stacks")
    ap.add_argument("--timeline", action="store_true",
                    help="record the telemetry timeline through the ramp "
                         "(qps steps stamped as marks; render with "
                         "tools_timeline.py --snapshot)")
    ap.add_argument("--causal", action="store_true",
                    help="after the ramp, run COZ-style virtual-speedup "
                         "experiments at the knee and merge the speedup "
                         "ledger into the artifact")
    ap.add_argument("--causal-phases", default="host_verify,serialize,"
                    "checkpoint",
                    help="comma-separated flowprof phases to experiment "
                         "on (default host_verify,serialize,checkpoint)")
    ap.add_argument("--causal-speedups", default="50",
                    help="comma-separated virtual speedup percentages "
                         "(default 50)")
    ap.add_argument("--causal-duration", type=float, default=4.0,
                    help="seconds of arrivals per causal probe — longer "
                         "probes mean a less noisy ledger (default 4)")
    ap.add_argument("--overload", action="store_true",
                    help="after the ramp, certify graceful degradation "
                         "at --overload-factor × the knee under chaos")
    ap.add_argument("--overload-factor", type=float, default=3.0,
                    help="storm arrival multiple of the knee (default 3)")
    ap.add_argument("--storm", type=float, default=6.0,
                    help="storm duration in seconds (default 6)")
    ap.add_argument("--recovery", type=float, default=30.0,
                    help="recovery wall bound in seconds (default 30)")
    ap.add_argument("--out", default="LOADTEST.json")
    args = ap.parse_args(argv)

    try:
        qps_steps = tuple(float(q) for q in args.qps.split(",") if q)
    except ValueError:
        print(f"loadgen: bad --qps {args.qps!r} (want e.g. 4,8,16)")
        return 2
    if not qps_steps or any(q <= 0 for q in qps_steps):
        print(f"loadgen: --qps steps must be positive: {args.qps!r}")
        return 2

    causal_speedups: tuple = ()
    causal_phases: tuple = ()
    if args.causal:
        # validate the experiment grid BEFORE the ramp spends minutes
        # locating a knee the bad arguments would then waste
        try:
            causal_speedups = tuple(
                float(x) / 100.0
                for x in args.causal_speedups.split(",") if x
            )
        except ValueError:
            causal_speedups = ()
        if not causal_speedups or any(
            not 0.0 < x < 1.0 for x in causal_speedups
        ):
            print(f"loadgen: bad --causal-speedups "
                  f"{args.causal_speedups!r} (want e.g. 25,50 — "
                  "percentages strictly between 0 and 100)")
            return 2
        from corda_tpu.observability.flowprof import PHASES

        causal_phases = tuple(
            p for p in args.causal_phases.split(",") if p
        )
        unknown = [p for p in causal_phases if p not in PHASES]
        if not causal_phases or unknown:
            print(f"loadgen: bad --causal-phases {args.causal_phases!r}"
                  f" (unknown: {', '.join(unknown) or '<empty>'}; "
                  f"flowprof phases: {', '.join(PHASES)})")
            return 2

    from corda_tpu.tools.loadharness import (
        HarnessConfig,
        run_harness,
        write_loadtest,
    )

    chaos = None
    if args.chaos:
        from corda_tpu.faultinject import FaultPlan

        chaos = FaultPlan(
            seed=args.seed, drop_p=0.02, delay_p=0.05, delay_rounds=(1, 3),
        )
    cfg = HarnessConfig(
        qps_steps=qps_steps,
        step_duration_s=args.duration,
        seed=args.seed,
        p99_slo_s=args.p99,
        max_error_rate=args.max_error_rate,
        max_inflight=args.max_inflight,
        workload=args.workload,
        use_device=args.device,
        chaos=chaos,
        durable=args.durable,
        resilience=args.resilience,
        sampler=args.sampler,
    )
    if args.timeline:
        # the timeline rides the whole ramp: the harness stamps each
        # step's qps (and the knee) into the mark deque, and the ring
        # snapshot travels in the artifact for tools_timeline.py
        from corda_tpu.observability import configure_timeline
        from corda_tpu.observability.timeseries import timeline

        configure_timeline(enabled=True, cadence_s=0.5, reset=True)
    try:
        result = run_harness(cfg)
        if args.timeline:
            result["timeline"] = timeline().snapshot()
    finally:
        if args.timeline:
            configure_timeline(enabled=False, reset=True)
    path = write_loadtest(result, args.out)
    knee = result.get("knee")
    for step in result["steps"]:
        print(
            "loadgen: step {qps:g} qps — offered {offered}, completed "
            "{completed}, errors {errors}, shed {shed}, p50 {p50:.3f}s, "
            "p99 {p99:.3f}s, SLO {ok}".format(
                qps=step["qps"], offered=step["offered"],
                completed=step["completed"], errors=step["errors"],
                shed=step["shed"], p50=step["p50_s"], p99=step["p99_s"],
                ok="ok" if step["slo_ok"] else "BREACHED",
            )
        )
    if knee is None:
        print("loadgen: no step met the SLO — no knee "
              f"(p99 bound {args.p99}s); wrote {path}")
        return 1
    wf = knee.get("waterfall", {})
    top = sorted(
        ((p, v) for p, v in wf.get("phases", {}).items() if v > 0),
        key=lambda kv: -kv[1],
    )[:4]
    print(
        f"loadgen: knee {knee['qps']:g} qps (p99 {knee['p99_s']:.3f}s); "
        "top phases: "
        + ", ".join(f"{p} {v:.2f}s" for p, v in top)
    )
    if args.causal:
        from corda_tpu.tools.loadharness import run_causal

        causal = run_causal(
            cfg, knee["qps"], phases=causal_phases,
            speedups=causal_speedups,
            probe_duration_s=args.causal_duration,
        )
        result["causal"] = causal
        path = write_loadtest(result, args.out)
        print(f"loadgen: causal baseline {causal['baseline_qps']:.1f} "
              "qps; speedup ledger:")
        for row in causal["ledger"]:
            print(
                "loadgen:   {phase} +{sp:g}% -> {gain:+.1f} qps "
                "({pct:+.1f}%)".format(
                    phase=row["phase"], sp=row["speedup_pct"],
                    gain=row["predicted_gain_qps"],
                    pct=row["predicted_gain_pct"],
                )
            )
    if args.overload:
        from corda_tpu.tools.loadharness import OverloadConfig, run_overload

        ocfg = OverloadConfig(
            base_qps=knee["qps"],
            overload_factor=args.overload_factor,
            storm_s=args.storm,
            recovery_s=args.recovery,
            # deadline = caller's give-up point, a few multiples of the
            # SLO target — not the SLO itself (under storm backoffs a
            # 1×p99 deadline kills every retransmitting flow)
            deadline_s=3.0 * args.p99,
            slo_p99_s=args.p99,
            workload=args.workload,
            seed=args.seed,
            durable=args.durable,
            use_device=args.device,
        )
        section = run_overload(ocfg)["overload"]
        result["overload"] = section
        path = write_loadtest(result, args.out)
        print(
            "loadgen: overload {oq:g} qps ({f:g}x knee) — goodput "
            "{gr:.0%} of baseline (floor {gf:.0%}), recovered to "
            "{rr:.0%} in {rw:.1f}s, rejected {rej}, shed {shed}, "
            "retry budget {gr_n}/{earn:g}".format(
                oq=section["overload_qps"], f=args.overload_factor,
                gr=section["goodput_ratio"], gf=section["goodput_floor"],
                rr=section["recovery_ratio"],
                rw=section["recovery_wall_s"],
                rej=section["admission_rejected"],
                shed=section["deadline_shed"],
                gr_n=section["retry_budget_granted"],
                earn=section["retry_budget_earned"],
            )
        )
        bad = [
            flag for flag in ("goodput_floor_ok", "recovery_ok",
                              "brownout_order_ok", "retry_budget_ok")
            if not section.get(flag)
        ]
        if bad:
            print(f"loadgen: overload certification FAILED: "
                  f"{', '.join(bad)}; wrote {path}")
            return 1
    print(f"loadgen: wrote {path}")
    print(json.dumps({"knee_qps": knee["qps"], "steps": len(result['steps'])}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
