"""Pallas block-size sweep — run on real TPU hardware.

Measures the ed25519 and ECDSA verify kernels across block widths
(lanes per grid step) and records throughput or the Mosaic failure per
configuration, settling the "why is the block pinned at 128?" question
with data (r2 VERDICT weak #7: the block-256 Mosaic crash was routed
around, not diagnosed).

    python tools_block_sweep.py            # writes BLOCK_SWEEP.json
                                           # + corda_tpu/serving/shapes.json

Each config compiles fresh (blocks are static args), runs a warm-up, then
times DEVICE_REPS enqueues with one deferred readback — the same
methodology as bench.py's device sections.

Besides the raw sweep record, the run emits its CHOSEN shapes — best
measured block width per kernel family plus the pad-bucket ladder — to
the checked-in ``corda_tpu/serving/shapes.json`` that the serving
scheduler loads at startup (corda_tpu/serving/shapes.py), so a re-sweep
on new hardware retunes the scheduler without a code change. The file is
only rewritten when at least one configuration measured successfully;
the scheduler's built-in default covers its absence entirely.
"""

from __future__ import annotations

import hashlib
import json
import time
import traceback

import numpy as np

BATCH = 4096
REPS = 8
ED25519_BLOCKS = (64, 128, 256, 512)
ECDSA_BLOCKS = (64, 128, 256)


def _ed25519_planes(b: int):
    from cryptography.hazmat.primitives.asymmetric import ed25519 as hostlib

    from corda_tpu.ops.ed25519 import L

    seed = hashlib.sha256(b"sweep-key").digest()
    sk = hostlib.Ed25519PrivateKey.from_private_bytes(seed)
    pk = sk.public_key().public_bytes_raw()
    y = np.zeros((b, 32), np.uint8)
    r = np.zeros((b, 32), np.uint8)
    s = np.zeros((b, 32), np.uint8)
    h = np.zeros((b, 32), np.uint8)
    for i in range(b):
        msg = b"CTSW" + hashlib.sha256(i.to_bytes(8, "little")).digest() + bytes(8)
        sig = sk.sign(msg)
        y[i] = np.frombuffer(pk, np.uint8)
        y[i, 31] &= 0x7F
        r[i] = np.frombuffer(sig[:32], np.uint8)
        s[i] = np.frombuffer(sig[32:], np.uint8)
        hv = int.from_bytes(
            hashlib.sha512(sig[:32] + pk + msg).digest(), "little"
        ) % L
        h[i] = np.frombuffer(hv.to_bytes(32, "little"), np.uint8)
    sign = np.full(b, pk[31] >> 7, np.int32)
    pre = np.ones(b, bool)
    return y, r, s, h, sign, pre


def _time_config(launch) -> dict:
    import jax.numpy as jnp

    mask = launch()
    ok = np.asarray(mask)
    if not ok.all():
        return {"error": f"kernel rejected valid lanes ({int(ok.sum())}/{len(ok)})"}
    warm = [launch() for _ in range(REPS)]
    np.asarray(jnp.stack(warm))
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        pending = [launch() for _ in range(REPS)]
        np.asarray(jnp.stack(pending))
        rates.append(BATCH * REPS / (time.perf_counter() - t0))
    rates.sort()
    return {"sigs_per_sec_median": round(rates[1], 1),
            "sigs_per_sec_best": round(rates[-1], 1)}


def sweep() -> dict:
    import jax

    out: dict = {"device": str(jax.devices()[0]), "batch": BATCH,
                 "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}

    y, r, s, h, sign, pre = _ed25519_planes(BATCH)
    from corda_tpu.ops.ed25519_pallas import ed25519_verify_pallas

    for blk in ED25519_BLOCKS:
        key = f"ed25519_block_{blk}"
        try:
            out[key] = _time_config(lambda: ed25519_verify_pallas(
                y, r, s, h, sign, pre, block=blk
            ))
        except Exception as e:
            out[key] = {"error": f"{type(e).__name__}: {e}"[:500]}
            traceback.print_exc()
        print(key, out[key], flush=True)

    # comb-vs-window A/B at the production block: the 8-bit fixed-base
    # comb trades wider constant-table selects for half the fixed-base
    # adds — this column is what arbitrates the CORDA_TPU_*_FIXED_WIN
    # default on real hardware (ab_* keys never feed shape selection)
    key = "ab_ed25519_fixedwin4_block_128"
    try:
        out[key] = _time_config(lambda: ed25519_verify_pallas(
            y, r, s, h, sign, pre, block=128, fixed_win=4
        ))
    except Exception as e:
        out[key] = {"error": f"{type(e).__name__}: {e}"[:500]}
        traceback.print_exc()
    print(key, out[key], flush=True)

    # ECDSA: one valid signature replicated across the batch (prep cost
    # off the timed path)
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature,
    )

    from corda_tpu.ops import secp256 as sp
    from corda_tpu.ops.secp256_pallas import ecdsa_verify_pallas

    import jax.numpy as jnp

    def ecdsa_planes(cv, curve_cls):
        priv = ec.generate_private_key(curve_cls())
        msg = b"sweep"
        der = priv.sign(msg, ec.ECDSA(hashes.SHA256()))
        rr, ss = decode_dss_signature(der)
        if ss > cv.n // 2:
            ss = cv.n - ss
        pk = priv.public_key().public_bytes(
            serialization.Encoding.X962,
            serialization.PublicFormat.CompressedPoint,
        )
        sig = rr.to_bytes(32, "big") + ss.to_bytes(32, "big")
        planes = sp._prep_byte_planes(
            cv.name, [pk] * BATCH, [sig] * BATCH, [msg] * BATCH, BATCH
        )
        qx, qy, u1b, u2b, ra, rb, rb_ok, pree = planes
        return (qx, qy, u1b, u2b, ra, rb,
                jnp.asarray(rb_ok), jnp.asarray(pree))

    def ecdsa_sweep(tag, cv, curve_cls, blocks, ab_configs):
        """Block sweep at the production tier config, plus A/B columns
        at block 128 pinning (radix, fixed_win) explicitly — the data
        that re-arbitrates the CORDA_TPU_*_RADIX / _FIXED_WIN defaults
        (r5's radix A/B predates the derived fold and the comb)."""
        args = ecdsa_planes(cv, curve_cls)
        for blk in blocks:
            key = f"ecdsa_{tag}_block_{blk}"
            try:
                out[key] = _time_config(lambda: ecdsa_verify_pallas(
                    cv.name, *args, block=blk
                ))
            except Exception as e:
                out[key] = {"error": f"{type(e).__name__}: {e}"[:500]}
                traceback.print_exc()
            print(key, out[key], flush=True)
        for ab_tag, radix, fixed_win in ab_configs:
            key = f"ab_ecdsa_{tag}_{ab_tag}_block_128"
            try:
                out[key] = _time_config(lambda: ecdsa_verify_pallas(
                    cv.name, *args, block=128,
                    radix=radix, fixed_win=fixed_win,
                ))
            except Exception as e:
                out[key] = {"error": f"{type(e).__name__}: {e}"[:500]}
                traceback.print_exc()
            print(key, out[key], flush=True)

    ecdsa_sweep("k1", sp.SECP256K1, ec.SECP256K1, ECDSA_BLOCKS,
                [("radix256", 256, None), ("fixedwin4", None, 4)])
    ecdsa_sweep("r1", sp.SECP256R1, ec.SECP256R1, (128,),
                [("radix256", 256, None), ("fixedwin4", None, 4)])
    return out


MAX_BUCKET = 8192  # bench batch shape ceiling (bench.py SIG_BATCH)


def choose_serving_shapes(results: dict) -> dict | None:
    """Distill a sweep record into the scheduler's shape table: the best
    measured block per kernel family and the power-of-two bucket ladder
    from the smallest winning block up to the bench batch shape. Returns
    None when nothing measured (sweep fully failed) — never downgrade the
    checked-in shapes on a broken run."""
    def best_block(prefix: str) -> int | None:
        rates = {}
        for key, val in results.items():
            if key.startswith(prefix) and isinstance(val, dict) \
                    and "sigs_per_sec_median" in val:
                rates[int(key[len(prefix):])] = val["sigs_per_sec_median"]
        return max(rates, key=rates.get) if rates else None

    ed = best_block("ed25519_block_")
    ec = best_block("ecdsa_k1_block_")
    if ed is None and ec is None:
        return None
    floor = min(b for b in (ed, ec) if b is not None)
    buckets, b = [], floor
    while b <= MAX_BUCKET:
        buckets.append(b)
        b <<= 1
    return {
        "source": "tools_block_sweep",
        "captured_at": results.get("captured_at"),
        "device": results.get("device"),
        "ed25519_block": ed,
        "ecdsa_block": ec,
        "buckets": buckets,
    }


def emit_serving_shapes(results: dict) -> None:
    import os

    shapes = choose_serving_shapes(results)
    if shapes is None:
        print("block sweep measured nothing; serving/shapes.json unchanged")
        return
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "corda_tpu", "serving", "shapes.json",
    )
    with open(path, "w") as f:
        json.dump(shapes, f, indent=1, sort_keys=True)
        f.write("\n")
    print("wrote", path, json.dumps(shapes))


if __name__ == "__main__":
    results = sweep()
    with open("BLOCK_SWEEP.json", "w") as f:
        json.dump(results, f, indent=1, sort_keys=True)
        f.write("\n")
    emit_serving_shapes(results)
    print(json.dumps(results))
