"""Cluster observatory dump CLI (docs/OBSERVABILITY.md §Cluster
observatory).

Runs one notarised payment across an in-process 3-node mock network
with hop recording + edge telemetry + tracing forced on, assembles the
payment's DISTRIBUTED trace (node-annotated spans, synthetic
``net.transit`` hop spans, the cross-node critical path) and the
federated cluster snapshot, and writes both as ONE JSON artifact:

    {"schema": 1, "trace": <TraceAssembler.assemble()>,
     "federation": <federated_snapshot()>}

    python tools_cluster_dump.py                       # CLUSTER.json
    python tools_cluster_dump.py --out /tmp/cluster.json

Knobs:

    --out PATH       output path (default CLUSTER.json)
    --amount N       payment amount in GBP minor units (default 250)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

ROOT = Path(__file__).parent
sys.path.insert(0, str(ROOT))

DUMP_SCHEMA = 1


def run_dump() -> dict:
    """The 3-node payment demo: returns the combined artifact body."""
    from corda_tpu.finance import CashIssueFlow, CashPaymentFlow
    from corda_tpu.messaging.netstats import configure_netstats
    from corda_tpu.observability import (
        TraceAssembler,
        configure_tracing,
        federated_snapshot,
    )
    from corda_tpu.observability.cluster import configure_cluster
    from corda_tpu.observability.flowprof import configure_flowprof
    from corda_tpu.testing import MockNetworkNodes
    from corda_tpu.verifier import BatchedVerifierService

    configure_tracing(sample_rate=1.0)
    configure_flowprof(enabled=True, reset=True)
    configure_cluster(enabled=True, reset=True)
    configure_netstats(enabled=True, reset=True)
    try:
        with MockNetworkNodes() as net:
            alice = net.create_node("DumpAlice")
            bob = net.create_node("DumpBob")
            notary = net.create_notary_node("DumpNotary")
            vsvc = BatchedVerifierService(use_device=False)
            alice.services.transaction_verifier_service = vsvc
            alice.run_flow(
                CashIssueFlow(1000, "GBP", b"\x0c", notary.party)
            )
            handle = alice.smm.start_flow(
                CashPaymentFlow(250, "GBP", bob.party)
            )
            handle.result.result(timeout=120)
            # responder spans land at FINISH time and can trail the
            # initiator's result — poll until all 3 nodes appear
            import time
            deadline = time.monotonic() + 15.0
            while True:
                trace = TraceAssembler(net).assemble(
                    flow_id=handle.flow_id
                )
                if len(trace.get("nodes", ())) >= 3 \
                        or time.monotonic() >= deadline:
                    break
                time.sleep(0.05)
            federation = federated_snapshot(net)
            vsvc.shutdown()
    finally:
        configure_netstats(enabled=False, reset=True)
        configure_cluster(enabled=False, reset=True)
        configure_flowprof(enabled=False, reset=True)
        configure_tracing(sample_rate=0.0)
    return {"schema": DUMP_SCHEMA, "trace": trace,
            "federation": federation}


def write_dump(doc: dict, path: str) -> str:
    """Atomic write (tmp+rename — the BASELINE/LOADTEST idiom)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="CLUSTER.json")
    args = ap.parse_args(argv)

    doc = run_dump()
    path = write_dump(doc, args.out)
    trace = doc["trace"]
    cp = trace.get("critical_path") or {}
    bound = cp.get("bound_by") or {}
    print(
        "cluster-dump: trace {tid} — {nodes} nodes, {spans} spans, "
        "{hops} hops (transit p99 {p99:.4f}s)".format(
            tid=(trace.get("trace_id") or "?")[:16],
            nodes=len(trace.get("nodes", ())),
            spans=len(trace.get("spans", ())),
            hops=trace.get("transit", {}).get("count", 0),
            p99=trace.get("transit", {}).get("p99_s", 0.0),
        )
    )
    if bound:
        print(
            "cluster-dump: bound by {node} {kind} {phase} "
            "({seconds:.4f}s, {share:.0%} of end-to-end)".format(
                node=bound.get("node"), kind=bound.get("kind"),
                phase=bound.get("phase"),
                seconds=bound.get("seconds", 0.0),
                share=bound.get("share", 0.0),
            )
        )
    rollup = doc["federation"].get("rollup", {})
    print(
        "cluster-dump: federation — {n} nodes, cluster p99 "
        "{p99:.4f}s, unhealthy {unhealthy}; wrote {path}".format(
            n=rollup.get("n_nodes", 0),
            p99=rollup.get("cluster_p99_s", 0.0),
            unhealthy=rollup.get("unhealthy_nodes", []),
            path=path,
        )
    )
    if trace.get("transit", {}).get("count", 0) < 2:
        print("cluster-dump: WARNING — fewer than 2 hops assembled; "
              "the trace join likely failed")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
